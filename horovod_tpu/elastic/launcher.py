"""Elastic launch entry point for ``horovodrun-tpu``.

Reference: /root/reference/horovod/runner/gloo_run.py launch_gloo_elastic
(:276-324) — start a rendezvous with live elastic handlers, build the
driver, and hand it a ``create_worker_fn`` that execs the user command on
the assigned host and kills the process tree when the driver's shutdown
event or the host's change event fires.
"""

import os
import random
import shutil
import socket
import tempfile
import threading
import time
import uuid
from typing import List, Optional

from ..runner import config_parser
from ..runner.exec_run import is_local_host, slot_env, _remote_command
from ..runner.hosts import SlotInfo
from ..runner.launch import free_port
from ..runner.rendezvous import RendezvousServer
from ..runner.safe_exec import safe_exec
from .discovery import FixedHosts, HostDiscoveryScript
from .driver import ElasticDriver
from .rendezvous import attach_elastic_handlers


def _make_create_worker_fn(command, rendezvous, rendezvous_addr: str,
                           rendezvous_port: int, base_env: dict,
                           output_dir: Optional[str] = None):
    """Build the driver's create_worker_fn (reference gloo_run.py:
    _exec_command_fn + get_run_command)."""

    def create_worker(slot_info: SlotInfo, events: List[threading.Event]):
        # The driver publishes the generation's coordinator address to the
        # rendezvous before spawning, so reading it here is race-free.
        coord = rendezvous.get("coordinator", "addr")
        coordinator_addr = coord.decode() if coord else ""
        env = slot_env(slot_info, coordinator_addr,
                       rendezvous_addr=rendezvous_addr,
                       rendezvous_port=rendezvous_port,
                       elastic=True, base_env=base_env)
        if is_local_host(slot_info.hostname):
            cmd = list(command)
        else:
            cmd = _remote_command(command, env, slot_info.hostname,
                                  ("PATH", "PYTHONPATH", "JAX_PLATFORMS",
                                   "XLA_FLAGS"))
        stop = threading.Event()

        def watch_events():
            while not stop.is_set():
                if any(e.is_set() for e in events):
                    stop.set()
                    return
                time.sleep(0.1)

        watcher = threading.Thread(target=watch_events, daemon=True)
        watcher.start()
        out_file = None
        exit_info: dict = {}
        try:
            if output_dir:
                os.makedirs(output_dir, exist_ok=True)
                out_file = open(
                    os.path.join(output_dir,
                                 f"{slot_info.hostname}.{slot_info.local_rank}"
                                 f".log"), "w", buffering=1)
            code = safe_exec(
                cmd, env=env,
                stdout_prefix=f"[{slot_info.rank}]<stdout> ",
                stop_event=stop, stdout_file=out_file, exit_info=exit_info)
        finally:
            stop.set()
            if out_file:
                out_file.close()
        # exit_time is captured at wait() — before the stdout drain — so
        # cascade-root ordering reflects actual death order.
        return code, exit_info.get("exit_time", time.time())

    return create_worker


def launch_elastic(args) -> int:
    """Run an elastic job from parsed ``horovodrun-tpu`` args
    (reference launch.py:574 _run_elastic)."""
    # These knobs steer the LAUNCHER process (journal on its KV store,
    # heartbeat monitor on its driver), not only workers, so CLI values
    # must land in this process's env before any Config() resolves them;
    # set_env_from_args below then propagates the same values to workers.
    for flag, var in (("rendezvous_dir", "HVD_TPU_RENDEZVOUS_DIR"),
                      ("heartbeat_interval", "HVD_TPU_HEARTBEAT_INTERVAL"),
                      ("heartbeat_timeout", "HVD_TPU_HEARTBEAT_TIMEOUT")):
        value = getattr(args, flag, None)
        if value is not None and value != "":
            os.environ[var] = str(value)
    if args.host_discovery_script:
        discovery = HostDiscoveryScript(args.host_discovery_script,
                                        default_slots=args.slots or 1)
    elif args.hosts:
        from ..runner.hosts import parse_hosts
        discovery = FixedHosts({h.hostname: h.slots
                                for h in parse_hosts(args.hosts)})
    else:
        raise ValueError(
            "elastic mode requires --host-discovery-script (or --hosts for "
            "a fixed set)")

    min_np = args.min_np or args.np or 1
    max_np = args.max_np

    rendezvous = RendezvousServer(verbose=args.verbose)
    # Before start(): on a hot-restart the store rebinds the previous
    # incarnation's persisted port immediately, and surviving workers'
    # beats must not be fsync-journaled as permanent state in the window
    # before attach_elastic_handlers runs.
    from .heartbeat import HEARTBEAT_SCOPE
    rendezvous.ephemeral_scopes.add(HEARTBEAT_SCOPE)
    rendezvous.start()

    driver = ElasticDriver(
        rendezvous, discovery, min_np=min_np, max_np=max_np,
        timeout=args.elastic_timeout, reset_limit=args.reset_limit)
    attach_elastic_handlers(rendezvous, driver)
    if rendezvous.replayed_entries:
        # Coordinator hot-restart: the KV store came back from its journal
        # (HVD_TPU_RENDEZVOUS_DIR), so this launcher is a restart, not a
        # fresh job — re-seed the driver's worker registry and blacklist
        # from the restored state instead of starting blind.
        driver.restore_from_rendezvous()

    # The elastic membership counters (driver.py) live in THIS process,
    # not in any worker, so the launcher serves its own scrape endpoint
    # when the metrics port is configured. Workers bind the same port on
    # their own hosts; a same-host collision just logs and continues.
    metrics_server = None
    try:
        metrics_port = int(os.environ.get(
            "HVD_TPU_METRICS_PORT",
            os.environ.get("HOROVOD_METRICS_PORT", "0")) or 0)
    except ValueError:
        metrics_port = 0
    if metrics_port > 0:
        from .. import metrics as _metrics
        try:
            metrics_server = _metrics.start_http_server(metrics_port)
        except (OSError, OverflowError, ValueError) as e:
            import logging
            logging.getLogger("horovod_tpu.elastic").warning(
                "elastic launcher: could not bind metrics endpoint on "
                "port %d: %s", metrics_port, e)

    def publish_coordinator(assignment_list):
        # New generation -> new JAX coordinator on the new rank-0 host.
        head = assignment_list[0]
        host = "127.0.0.1" if is_local_host(head.hostname) \
            else head.hostname
        port = random.randint(29500, 59999) if not is_local_host(
            head.hostname) else free_port()
        rendezvous.put("coordinator", "addr", f"{host}:{port}".encode())

    driver.set_assignments_callback(publish_coordinator)

    base_env = config_parser.set_env_from_args(dict(os.environ), args)
    # Job-scoped durable-commit directory: workers persist every commit()
    # here so a slot respawned after a hard kill restores its last commit
    # (see elastic/run.py STATE_DIR_ENV). A user-provided
    # HVD_TPU_ELASTIC_STATE_DIR is honored (point it at shared storage on
    # multi-host clusters — a launcher-local mkdtemp path does not exist on
    # remote hosts, where workers then mkdir it themselves per-host and
    # recovery degrades to the rank-0 broadcast). Only the dir this
    # launcher created is cleaned up afterwards.
    state_dir = base_env.get("HVD_TPU_ELASTIC_STATE_DIR")
    own_state_dir = None
    if not state_dir:
        state_dir = own_state_dir = tempfile.mkdtemp(
            prefix="hvd_tpu_elastic_job_")
        base_env["HVD_TPU_ELASTIC_STATE_DIR"] = state_dir
    # Job-unique token namespacing the commit files, so a reused shared
    # state dir never resurrects a previous job's state.
    base_env.setdefault("HVD_TPU_ELASTIC_JOB_ID",
                        uuid.uuid4().hex[:12])
    rdv_host = socket.gethostname()
    try:
        socket.gethostbyname(rdv_host)
    except OSError:
        rdv_host = "127.0.0.1"

    create_worker_fn = _make_create_worker_fn(
        args.command, rendezvous, rdv_host, rendezvous.port, base_env,
        output_dir=args.output_filename)

    # First generation targets the requested -np (reference: launch_gloo_
    # elastic starts at settings.num_proc); later resumes shrink/grow within
    # [min_np, max_np].
    try:
        driver.start(args.np or min_np, create_worker_fn)
        results = driver.get_results()
        driver.stop()
    except TimeoutError as e:
        # wait_for_available_slots gave up: not enough discoverable slots
        # (reference scenario: min-np timeout). Surface the reason cleanly
        # instead of a traceback.
        driver.stop()
        import sys
        sys.stderr.write(f"horovodrun-tpu: {e}\n")
        return 1
    finally:
        if metrics_server is not None:
            from .. import metrics as _metrics
            _metrics.stop_http_server(metrics_server)
        if own_state_dir:
            shutil.rmtree(own_state_dir, ignore_errors=True)

    if results.error_message:
        import sys
        sys.stderr.write(results.error_message + "\n")
        return 1
    for name, (code, _ts) in results.worker_results.items():
        if code != 0:
            import sys
            sys.stderr.write(
                f"horovodrun-tpu: elastic worker {name} exited with "
                f"code {code}\n")
            return code
    return 0
