"""Worker state registry: barriers worker generations through resets.

Reference: /root/reference/horovod/runner/elastic/registration.py.
Every worker generation records READY (re-rendezvoused) / SUCCESS /
FAILURE; a threading.Barrier sized to the world fires the transition
action once all are in: stop on any SUCCESS or total failure, otherwise
blacklist failing hosts and resume with a fresh rendezvous. A worker that
recorded READY but later fails resets the barrier so it is not counted
twice.
"""

import logging
import threading
from typing import Optional, Set, Tuple

from .. import metrics as _metrics

#: Recovery activity, launcher-side: dashboards watch the blacklist gauge
#: climb and the restart counter (elastic/run.py) tick to see a job
#: surviving failures — neither is visible from any single worker.
_M_BLACKLISTED = _metrics.gauge(
    "hvd_tpu_elastic_blacklisted_hosts",
    "Hosts currently blacklisted after worker failures.")

READY = "READY"
SUCCESS = "SUCCESS"
FAILURE = "FAILURE"

RESET_LIMIT_EXCEEDED_MESSAGE = (
    "Exceeded the permitted number of elastic resets ({}); terminating the "
    "job. A reset limit typically guards against thrashing clusters; raise "
    "--reset-limit if frequent membership changes are expected.")

log = logging.getLogger("horovod_tpu.elastic")


class WorkerStateRegistry:
    def __init__(self, driver, host_manager, reset_limit: Optional[int] = None):
        self._driver = driver
        self._host_manager = host_manager
        self._reset_limit = reset_limit
        self._reset_count = 0
        self._lock = threading.Lock()
        self._states: dict = {}
        self._by_state: dict = {READY: set(), SUCCESS: set(), FAILURE: set()}
        self._failure_order: list = []   # ((host, slot), exit_ts, arrival_i)
        self._barrier: Optional[threading.Barrier] = None
        self._rendezvous_id = 0
        self._size = 0

    # -- introspection ------------------------------------------------------
    def get(self, state: str) -> Set[Tuple[str, int]]:
        return self._by_state.setdefault(state, set())

    def count(self, state: str) -> int:
        return len(self.get(state))

    def recorded_slots(self):
        return self._states.keys()

    def size(self) -> int:
        return self._size

    def last_rendezvous(self) -> int:
        return self._rendezvous_id

    # -- lifecycle ----------------------------------------------------------
    def reset(self, size: int) -> None:
        with self._lock:
            self._states.clear()
            for s in self._by_state.values():
                s.clear()
            self._failure_order.clear()
            self._barrier = threading.Barrier(parties=size,
                                              action=self._on_all_recorded)
            self._rendezvous_id += 1
            self._size = size

    def record_ready(self, host: str, slot: int) -> int:
        return self._record(host, slot, READY)

    def record_success(self, host: str, slot: int) -> int:
        return self._record(host, slot, SUCCESS)

    def record_failure(self, host: str, slot: int,
                       timestamp: Optional[float] = None) -> int:
        return self._record(host, slot, FAILURE, timestamp=timestamp)

    def _record(self, host: str, slot: int, state: str,
                timestamp: Optional[float] = None) -> int:
        if self._driver.finished():
            return self._rendezvous_id
        if self._host_manager.is_blacklisted(host):
            return self._rendezvous_id

        key = (host, slot)
        with self._lock:
            prior = self._states.get(key)
            if prior is not None:
                if state == FAILURE and prior != FAILURE:
                    # The READY thread for this worker is already parked at
                    # the barrier; reset it so the worker is counted once.
                    log.info("elastic: %s[%s] %s -> FAILURE, resetting "
                             "barrier", host, slot, prior)
                    self._barrier.reset()
                else:
                    # Duplicate record (e.g. a retried rendezvous GET):
                    # do NOT wait at the barrier again or the party count
                    # would be inflated and the generation would hang.
                    log.debug("elastic: ignoring duplicate state %s for "
                              "%s[%s] (have %s)", state, host, slot, prior)
                    return self._rendezvous_id
            self._states[key] = state
            self.get(state).add(key)
            if state == FAILURE:
                # (A duplicate FAILURE for this key early-returned above,
                # so each key appears at most once.) Record the worker-
                # reported exit timestamp alongside the arrival index:
                # record ARRIVAL order is not causal order (a slow
                # notification path can invert it), but exit timestamps
                # are captured at wait() by the per-worker runner threads
                # on the launcher host, so they share a clock and order
                # causally — the cascade-root heuristic sorts on them.
                self._failure_order.append(
                    (key, timestamp if timestamp is not None
                     else float("inf"), len(self._failure_order)))
            rid = self._rendezvous_id

        return self._wait(key, state, rid)

    def _wait(self, key, state, rendezvous_id: int) -> int:
        while True:
            try:
                self._barrier.wait()
                return rendezvous_id
            except threading.BrokenBarrierError:
                if self._barrier.broken:
                    raise
                with self._lock:
                    rendezvous_id = self._rendezvous_id
                    saved = self._states.get(key, state)
                    if saved != state:
                        raise RuntimeError(
                            f"elastic worker state {state} overridden by "
                            f"{saved}") from None

    def _blacklist(self, host: str) -> None:
        # Through the driver when it has the persistent path (rendezvous-
        # journaled blacklist survives coordinator restarts); the plain
        # host-manager call keeps driver-less unit doubles working.
        if hasattr(self._driver, "blacklist_host"):
            self._driver.blacklist_host(host)
        else:
            self._host_manager.blacklist(host)

    # -- barrier action (runs on the last arriving thread) -------------------
    def _on_all_recorded(self):
        if self.count(SUCCESS) > 0:
            log.info("elastic: %d worker(s) succeeded; stopping job",
                     self.count(SUCCESS))
            self._driver.stop()
            return
        respawn_all = False
        if self.count(FAILURE) == self._size:
            # Total loss of the generation. On this runtime a single hard
            # worker death takes down every peer: survivors block in a
            # collective, the JAX coordination service detects the missed
            # heartbeat and fatally terminates them. "All failed" therefore
            # does NOT mean every host is bad — the root cause is the
            # FIRST recorded failure (peers die a heartbeat-timeout later).
            # Blacklist only the root host and respawn the remainder; a
            # genuinely-broken job converges anyway (one blacklist per
            # generation until min_np is unreachable or reset_limit hits).
            # Root = earliest worker-reported exit timestamp (arrival
            # index breaks ties and covers records without a timestamp).
            ordered = sorted(self._failure_order,
                             key=lambda e: (e[1], e[2]))
            root = ordered[0][0] if ordered else None
            survivors = [h for h, _ in self.recorded_slots()
                         if root is not None and h != root[0]
                         and not self._host_manager.is_blacklisted(h)]
            if root is None or not survivors:
                log.error("elastic: all %d workers failed with no "
                          "surviving host; stopping job", self._size)
                self._driver.stop(error_message=(
                    f"all {self._size} elastic worker(s) failed and no "
                    "healthy host remains to recover on; terminating the "
                    "job. Check the per-worker logs for the root failure."))
                return
            log.warning(
                "elastic: all %d workers failed; treating as a cascade "
                "rooted at %s[%s] (first failure) — blacklisting %s and "
                "respawning the surviving hosts %s",
                self._size, root[0], root[1], root[0], survivors)
            self._blacklist(root[0])
            respawn_all = True
        else:
            for host, _slot in self.get(FAILURE):
                self._blacklist(host)
        _M_BLACKLISTED.set(self._host_manager.blacklisted_count())
        if all(self._host_manager.is_blacklisted(h)
               for h, _ in self.recorded_slots()):
            log.error("elastic: every active host is blacklisted; stopping")
            self._driver.stop(error_message=(
                "every host in the job has been blacklisted after worker "
                "failures; no host remains to run on. Terminating the job."))
            return
        if self._reset_limit is not None \
                and self._reset_count >= self._reset_limit:
            self._driver.stop(error_message=RESET_LIMIT_EXCEEDED_MESSAGE
                              .format(self._reset_limit))
            return
        try:
            self._reset_count += 1
            self._driver.resume(respawn_all=respawn_all)
        except Exception as e:
            log.exception("elastic: failed to resume with new hosts")
            # Without an error message a job whose every worker died before
            # finishing would report success (empty worker_results).
            self._driver.stop(error_message=(
                f"elastic job could not form a new generation after worker "
                f"failures: {e}"))
