"""Worker-side elastic state: commit / restore / sync.

Reference: /root/reference/horovod/common/elastic.py (State:27-108,
ObjectState:111-144) and horovod/torch/elastic.py (TorchState with
state_dict save/restore). The TPU-native variant adds :class:`JaxState`,
which snapshots jax pytrees to host memory (``jax.device_get``) on
``save()`` and re-stages them (``jax.device_put``) on ``restore()`` —
the moral equivalent of the reference's GPU->host checkpoint copies.
"""

import queue
from typing import Any, Callable, Dict, List, Optional

from .. import faults as _faults
from ..exceptions import HostsUpdatedInterrupt

# Chaos site for the elastic step loop: one hit per State.commit(), so
# ``worker.step:crash:step=N`` hard-kills this worker at its N-th commit
# — the deterministic stand-in for `kill -9` in recovery drills. Fired
# BEFORE save(), so a crash here loses exactly the uncommitted step (the
# same contract as a real mid-step kill). A ``preempt`` rule here instead
# *announces* this worker's host to the driver's graceful-drain path (the
# deterministic stand-in for a fleet reclaim notice) and lets the commit
# proceed — so the notice always post-dates a fresh commit, exactly like
# a real scheduler warning landing between steps.
_FP_STEP = _faults.FaultPoint("worker.step")


def _announce_preemption(grace: float) -> None:
    from .worker import notification_manager
    notification_manager.send_preemption_notice(grace)


def _default_bcast_object(obj, root_rank=0, name=None):
    from ..functions import broadcast_object
    return broadcast_object(obj, root_rank=root_rank, name=name)


def _default_get_rank():
    from .. import basics
    return basics.rank()


class State:
    """Tracks in-memory state that must survive worker membership changes.

    ``commit()`` = ``save()`` + host-update check; a pending host update
    raises :class:`HostsUpdatedInterrupt` *synchronously across ranks* (the
    pending-update timestamp is broadcast from rank 0 before raising, so
    every worker interrupts at the same batch — reference
    common/elastic.py:73-95).
    """

    def __init__(self, bcast_object: Optional[Callable] = None,
                 get_rank: Optional[Callable] = None):
        self._bcast_object = bcast_object or _default_bcast_object
        self._rank = get_rank or _default_get_rank
        self._host_messages: "queue.Queue" = queue.Queue()
        self._last_updated_timestamp = 0
        self._reset_callbacks: List[Callable] = []

    def register_reset_callbacks(self, callbacks: List[Callable]) -> None:
        """Callbacks run after every reset (e.g. re-scale the LR by the new
        world size)."""
        self._reset_callbacks.extend(callbacks)

    def on_reset(self) -> None:
        self._host_messages = queue.Queue()
        self.reset()
        for cb in self._reset_callbacks:
            cb()

    def on_hosts_updated(self, timestamp: float) -> None:
        """Called by the worker notification service thread."""
        self._host_messages.put(timestamp)

    def commit(self) -> None:
        _FP_STEP.fire(preempt=_announce_preemption)
        self.save()
        # Durability on EVERY commit, not just the graceful re-exec path:
        # a worker hard-killed by the runtime (peer-death cascade through
        # the JAX coordination service) must still find its last commit on
        # disk when the driver respawns its slot.
        from .run import persist_committed_state
        persist_committed_state(self)
        self.check_host_updates()

    def check_host_updates(self) -> None:
        last = prev = self._last_updated_timestamp
        while not self._host_messages.empty():
            ts = self._host_messages.get()
            last = max(last, ts)
        # Sync across ranks so every worker raises on the same step.
        prev, self._last_updated_timestamp = self._bcast_object(
            (prev, last), name="_hvd_elastic_host_ts")
        if self._last_updated_timestamp > prev:
            raise HostsUpdatedInterrupt()

    # -- to be provided by subclasses ---------------------------------------
    def save(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError

    def reset(self) -> None:
        pass


class ObjectState(State):
    """State for plain Python attribute values (reference
    common/elastic.py:111-144): each kwarg becomes an attribute; ``sync``
    broadcasts the committed dict from rank 0."""

    def __init__(self, bcast_object: Optional[Callable] = None,
                 get_rank: Optional[Callable] = None, **kwargs):
        self._saved_state: Dict[str, Any] = kwargs
        super().__init__(bcast_object=bcast_object, get_rank=get_rank)
        self._apply_saved()

    def save(self) -> None:
        self._saved_state = {k: getattr(self, k) for k in self._saved_state}

    def restore(self) -> None:
        self._apply_saved()

    def sync(self) -> None:
        if self._saved_state:
            self._saved_state = self._bcast_object(
                self._saved_state, name="_hvd_elastic_object_state")
            self._apply_saved()

    def _apply_saved(self) -> None:
        for k, v in self._saved_state.items():
            setattr(self, k, v)


class JaxState(ObjectState):
    """Elastic state for jax pytrees (params / optimizer state / train
    state) plus plain scalars.

    Any attribute whose value is a jax pytree containing jax Arrays is
    snapshotted to host numpy on ``save()`` (device memory does not survive
    a mesh re-initialization) and re-staged with ``jax.device_put`` on
    ``restore()``/``sync()``. Scalars ride the ObjectState path.

    Example::

        state = JaxState(params=params, opt_state=opt_state, batch=0)
        state.commit()           # after an optimizer step
        ...
        state.restore()          # rolls params/opt_state back
    """

    def __init__(self, bcast_object: Optional[Callable] = None,
                 get_rank: Optional[Callable] = None, sharding=None, **kwargs):
        self._sharding = sharding   # optional target sharding for restore
        super().__init__(bcast_object=bcast_object, get_rank=get_rank,
                         **kwargs)

    def _to_host(self, value):
        """Per-leaf host snapshot: array leaves become numpy, every other
        leaf (step counters, schedules, static fields of a TrainState) passes
        through — mixed pytrees must not silently keep live device-array
        references, which would dangle across a mesh re-initialization."""
        import jax
        import numpy as np

        def leaf(l):
            if isinstance(l, jax.Array):
                return np.asarray(jax.device_get(l))
            return l
        return jax.tree_util.tree_map(leaf, value)

    def _to_device(self, value):
        import jax
        import numpy as np

        def is_arr(l):
            return isinstance(l, (jax.Array, np.ndarray))

        # whole-tree device_put with the target sharding only for pure-array
        # pytrees; plain scalars (epoch/batch counters) must stay Python
        # values — promoting them to jax.Arrays breaks hashing/serialization
        leaves = jax.tree_util.tree_leaves(value)
        if self._sharding is not None and leaves and all(map(is_arr, leaves)):
            try:
                return jax.device_put(value, self._sharding)
            except (TypeError, ValueError):
                pass
        return jax.tree_util.tree_map(
            lambda l: jax.device_put(l) if is_arr(l) else l, value)

    def save(self) -> None:
        self._saved_state = {
            k: self._to_host(getattr(self, k)) for k in self._saved_state}

    def _apply_saved(self) -> None:
        for k, v in self._saved_state.items():
            setattr(self, k, self._to_device(v))
