"""Elastic (fault-tolerant, resizable) training for horovod_tpu.

TPU-native re-design of the reference elastic stack
(/root/reference/horovod/runner/elastic/{driver,discovery,registration,
worker}.py and horovod/common/elastic.py):

* the **launcher side** keeps the reference architecture — a driver with a
  1 Hz host-discovery thread, stable rank assignments, a worker-state
  registry with host blacklisting, and a KV rendezvous the workers re-query
  on reset — because that host-plane design is framework-agnostic and
  sound;
* the **worker side** is JAX-native: a reset tears down and re-creates the
  JAX distributed runtime and world mesh (the analogue of the reference's
  ``hvd.shutdown(); hvd.init()`` gloo re-rendezvous,
  torch/elastic.py:46-49 + gloo/gloo_context.cc:157-170), and state
  commit/restore moves jax pytrees between device and host memory.

User API (mirrors ``hvd.elastic``)::

    import horovod_tpu as hvd

    state = hvd.elastic.JaxState(params=params, opt_state=opt_state, epoch=0)

    @hvd.elastic.run
    def train(state):
        for state.epoch in range(state.epoch, epochs):
            ...
            state.commit()

    train(state)
"""

from .state import State, ObjectState, JaxState  # noqa: F401
from .run import fetch_mesh_shape, run, run_fn  # noqa: F401
from .discovery import (  # noqa: F401
    HostDiscovery, HostDiscoveryScript, FixedHosts, HostManager,
    DiscoveredHosts,
)
from .registration import WorkerStateRegistry, READY, SUCCESS, FAILURE  # noqa: F401
from .driver import ElasticDriver  # noqa: F401
from .callbacks import (  # noqa: F401
    CommitStateCallback, UpdateBatchStateCallback, UpdateEpochStateCallback,
)
