"""Worker-side notification channel for host membership changes.

Reference: /root/reference/horovod/runner/elastic/worker.py — the rank-0
worker runs a small authenticated TCP service; the driver pushes
"hosts updated" timestamps to it; the manager fans the timestamp out to
registered elastic State objects, which raise HostsUpdatedInterrupt at the
next commit. The worker advertises the service's addresses + per-job
secret to the launcher through the rendezvous KV store
(scope ``worker_addresses``, key ``hostname:local_rank``).
"""

import logging
import os
import pickle
import threading
from typing import Optional

from .. import faults as _faults
from .. import retry as _retry
from ..runner.network import (AckResponse, BasicClient, BasicService,
                              make_secret_key)
from .heartbeat import HeartbeatSender

log = logging.getLogger("horovod_tpu.elastic")

PUT_WORKER_ADDRESSES = "worker_addresses"

# Chaos sites for the worker<->driver control channel: registration (the
# KV put advertising this worker's notification service) and the driver's
# hosts-updated pushes. Both simulate as transient network failures.
_FP_REGISTER = _faults.FaultPoint("worker.register",
                                  exc=_faults.InjectedTransientFault)
_FP_NOTIFY = _faults.FaultPoint("elastic.notify",
                                exc=_faults.InjectedTransientFault)


class HostsUpdatedRequest:
    def __init__(self, timestamp: float):
        self.timestamp = timestamp


class WorkerNotificationService(BasicService):
    NAME = "hvd-tpu worker notification service"

    def __init__(self, key: bytes, manager: "WorkerNotificationManager"):
        super().__init__(self.NAME, key)
        self._manager = manager

    def _handle(self, req, client_address):
        if isinstance(req, HostsUpdatedRequest):
            self._manager.handle_hosts_updated(req.timestamp)
            return AckResponse()
        return super()._handle(req, client_address)


class WorkerNotificationClient(BasicClient):
    def __init__(self, addresses, key: bytes, timeout: float = 10.0):
        super().__init__(WorkerNotificationService.NAME, addresses, key,
                         timeout=timeout)

    def notify_hosts_updated(self, timestamp: float) -> None:
        _FP_NOTIFY.fire()
        self._send(HostsUpdatedRequest(timestamp))


class WorkerNotificationManager:
    """Process-wide singleton on each worker (reference worker.py:37-81)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._service: Optional[WorkerNotificationService] = None
        self._listeners = set()
        self._heartbeat: Optional[HeartbeatSender] = None
        self._client = None
        self._hostname: Optional[str] = None

    def init(self, rendezvous_addr: Optional[str] = None,
             rendezvous_port: Optional[int] = None,
             hostname: Optional[str] = None,
             local_rank: Optional[int] = None) -> None:
        with self._lock:
            if self._service:
                return
            rendezvous_addr = rendezvous_addr or \
                os.environ.get("HVD_TPU_RENDEZVOUS_ADDR") or \
                os.environ.get("HOROVOD_GLOO_RENDEZVOUS_ADDR")
            if not rendezvous_addr:
                return   # not an elastic launch; nothing to register with
            rendezvous_port = rendezvous_port if rendezvous_port is not None \
                else int(os.environ.get(
                    "HVD_TPU_RENDEZVOUS_PORT",
                    os.environ.get("HOROVOD_GLOO_RENDEZVOUS_PORT", 0)))
            hostname = hostname or os.environ.get(
                "HVD_TPU_HOSTNAME", os.environ.get("HOROVOD_HOSTNAME", ""))
            if local_rank is None:
                local_rank = int(os.environ.get(
                    "HVD_TPU_LOCAL_RANK",
                    os.environ.get("HOROVOD_LOCAL_RANK", 0)))

            key = make_secret_key()
            self._service = WorkerNotificationService(key, self)

            from ..runner.rendezvous import KVStoreClient

            # Registration is the driver's only way to interrupt this
            # worker on membership changes; a transient blip here must be
            # retried, not silently drop the worker off the notification
            # plane. ONE policy owns the budget: the client is built with
            # max_attempts=1 so its internal rendezvous.put policy cannot
            # nest inside this one and multiply attempts/deadline.
            client = KVStoreClient(rendezvous_addr, rendezvous_port,
                                   retry=_retry.RetryPolicy(max_attempts=1))
            payload = pickle.dumps((self._service.addresses(), key))

            def register():
                _FP_REGISTER.fire()
                client.put(PUT_WORKER_ADDRESSES,
                           f"{hostname}:{local_rank}", payload)

            def register_with_retries():
                _retry.RetryPolicy.from_config().call(
                    register, site="worker.register")

            # A coordinator epoch bump means the KV store restarted: any
            # scoped key the old incarnation lost (most critically our
            # notification address — the driver's only way to interrupt
            # this worker) must be re-registered, under the same retry
            # policy as first registration, instead of wedging on stale
            # state. The bump is observed on whatever op touches the
            # store next — in steady state, the next heartbeat PUT.
            def on_epoch_bump(old, new):
                log.warning(
                    "elastic: coordinator epoch bumped %d -> %d "
                    "(rendezvous restarted); re-registering this worker",
                    old, new)
                register_with_retries()
            client.on_epoch_bump = on_epoch_bump

            register_with_retries()

            # Per-rank liveness beats over the same client/channel. Rank
            # comes from the launch env; heartbeats pre-date init() so the
            # world may not exist yet.
            rank = os.environ.get("HVD_TPU_RANK",
                                  os.environ.get("HOROVOD_RANK", "?"))
            self._heartbeat = HeartbeatSender(client, hostname, local_rank,
                                              rank)
            self._heartbeat.start()
            # Kept for the preemption-notice PUT (send_preemption_notice):
            # notices ride the same KV channel as registration/beats.
            self._client = client
            self._hostname = hostname

    def send_preemption_notice(self, grace: float = 0.0) -> bool:
        """PUT a preemption notice for THIS worker's host to the journaled
        ``preempt`` scope — the drill path of the shared notice channel
        (the ``preempt`` fault kind lands here via the elastic State's
        commit fault point). Returns True when the notice reached the
        store; False on a non-elastic launch or a delivery failure (the
        driver's discovery poll is the production backstop, so best-effort
        is correct here)."""
        with self._lock:
            client, hostname = self._client, self._hostname
        if client is None or not hostname:
            return False
        from .preemption import PREEMPT_SCOPE, encode_notice
        try:
            client.put(PREEMPT_SCOPE, hostname, encode_notice(grace))
            log.warning("elastic: preemption notice sent for %s "
                        "(grace=%.1fs)", hostname, grace)
            return True
        except Exception:
            log.warning("elastic: preemption notice for %s not delivered",
                        hostname, exc_info=True)
            return False

    def send_sdc_report(self, kind: str, strikes: int = 1) -> bool:
        """PUT a silent-data-corruption quarantine report for THIS
        worker's host to the journaled ``sdc`` scope — the SDC policy
        calls it when local detections cross HVD_TPU_SDC_STRIKES
        (horovod_tpu/sdc/policy.py). Returns True when the report
        reached the store; False on a non-elastic launch or a delivery
        failure (best-effort, like the preemption notice: the training
        loop's skip/rollback reactions do not depend on the driver
        hearing about the offender)."""
        with self._lock:
            client, hostname = self._client, self._hostname
        if client is None or not hostname:
            return False
        from ..sdc.report import SDC_SCOPE, encode_report
        try:
            client.put(SDC_SCOPE, hostname, encode_report(kind, strikes))
            log.warning("elastic: SDC quarantine report sent for %s "
                        "(kind=%s, strikes=%d)", hostname, kind, strikes)
            return True
        except Exception:
            log.warning("elastic: SDC quarantine report for %s not "
                        "delivered", hostname, exc_info=True)
            return False

    def register_listener(self, listener) -> None:
        self._listeners.add(listener)

    def remove_listener(self, listener) -> None:
        self._listeners.discard(listener)

    def handle_hosts_updated(self, timestamp: float) -> None:
        for listener in list(self._listeners):
            listener.on_hosts_updated(timestamp)

    def shutdown(self) -> None:
        with self._lock:
            if self._heartbeat:
                self._heartbeat.stop()
                self._heartbeat = None
            if self._service:
                self._service.shutdown()
                self._service = None
            self._client = None
            self._hostname = None


notification_manager = WorkerNotificationManager()
