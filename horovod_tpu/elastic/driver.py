"""Launcher-side elastic driver.

Reference: /root/reference/horovod/runner/elastic/driver.py — ElasticDriver
owns a 1 Hz discovery thread, computes stable host/rank assignments on
membership change, re-publishes them to the rendezvous, notifies the
coordinator (rank-0) worker so it can interrupt training, and (re)spawns
worker processes on newly assigned slots. The data-plane consequence on
TPU: every reset the workers rebuild the JAX distributed runtime and the
device mesh; the driver only manages host membership.
"""

import json
import logging
import queue
import threading
import time
from collections import defaultdict
from typing import Callable, Dict, List, Optional, Tuple

from .. import config as _config
from .. import metrics as _metrics
from ..runner.hosts import HostInfo, SlotInfo, get_host_assignments
from .discovery import DiscoveredHosts, HostManager
from .heartbeat import HeartbeatMonitor
from ..sdc.report import SDC_SCOPE, decode_report, encode_report
from .preemption import PREEMPT_SCOPE, decode_notice, encode_notice
from .registration import WorkerStateRegistry
from .worker import PUT_WORKER_ADDRESSES, WorkerNotificationClient

#: rendezvous scope persisting blacklisted hostnames — journaled with the
#: rest of the store, so a restarted coordinator does not re-run doomed
#: hosts it already learned about. Values are the blacklist *reason*
#: (b"failure", ...); gracefully-drained hosts are never written here —
#: their durable record lives in the ``preempt`` scope instead, and is
#: deleted when the drain completes.
BLACKLIST_SCOPE = "blacklist"

#: rendezvous scope carrying the driver's current mesh plan (key
#: ``shape`` -> JSON ``{"axes": {...}, "policy": ..., "dropped": N}``).
#: Journaled like the blacklist, so a restarted coordinator resumes the
#: reshaped mesh instead of replanning from the configured one; workers
#: read it on reset (:func:`horovod_tpu.elastic.run.fetch_mesh_shape`)
#: to re-form the survivor mesh.
MESH_SCOPE = "mesh"

# Elastic membership events as counters: a flapping host shows up as a
# climbing add/remove rate on the driver's scrape, which no single worker
# can observe from inside its own generation.
_M_RESETS = _metrics.counter(
    "hvd_tpu_elastic_resets_total",
    "Elastic generation resets (resume() after membership change or "
    "worker failure).")
_M_RANK_ADDED = _metrics.counter(
    "hvd_tpu_elastic_rank_added_total",
    "Worker slots added relative to the previous elastic generation.")
_M_RANK_REMOVED = _metrics.counter(
    "hvd_tpu_elastic_rank_removed_total",
    "Worker slots removed relative to the previous elastic generation.")
_M_PREEMPTIONS = _metrics.counter(
    "hvd_tpu_elastic_preemptions_total",
    "Preemption notices processed by the elastic driver, by outcome: "
    "'drained' (graceful drain completed), 'immediate' (scale-down policy "
    "killed the host instead of draining).",
    labels=("outcome",))
_M_DRAIN_SECONDS = _metrics.histogram(
    "hvd_tpu_elastic_drain_seconds",
    "Wall time from a preemption notice to the drained host leaving the "
    "generation (final commit drained, survivors re-rendezvoused).")
_M_SCALE_EVENTS = _metrics.counter(
    "hvd_tpu_elastic_scale_events_total",
    "Deliberate elastic resizes, by direction: 'up' (debounced growth "
    "into new capacity), 'down' (preemption-notice shrink).",
    labels=("direction",))
_M_MESH_RESHAPES = _metrics.counter(
    "hvd_tpu_elastic_mesh_reshapes_total",
    "Mesh replans taken by the elastic driver's mesh plane "
    "(HVD_TPU_MESH_SHAPE set), by the reshape policy that produced the "
    "new shape ('shrink'/'degrade'/'strict') and the capacity direction "
    "('down' after host loss or drain, 'up' after re-admission). "
    "Launcher-side, like the reset counters.",
    labels=("policy", "direction"))
_M_QUARANTINED = _metrics.gauge(
    "hvd_tpu_sdc_quarantined_hosts",
    "Hosts quarantined for silent data corruption (blacklisted with "
    "reason 'sdc' after repeated guard/fingerprint strikes; persisted "
    "across coordinator restarts).")

DISCOVER_HOSTS_FREQUENCY_SECS = 1.0

log = logging.getLogger("horovod_tpu.elastic")

#: Placeholder returned for (host, slot) pairs with no current assignment.
INVALID_SLOT_INFO = SlotInfo(hostname="", rank=-1, local_rank=-1,
                             cross_rank=-1, size=-1, local_size=-1,
                             cross_size=-1)


class Timeout:
    """Deadline helper (reference runner/common/util/timeout.py)."""

    def __init__(self, seconds: float, message: str):
        self._deadline = time.monotonic() + seconds
        self._message = message

    def remaining(self) -> float:
        return max(0.0, self._deadline - time.monotonic())

    def check(self, activity: str) -> None:
        if time.monotonic() > self._deadline:
            raise TimeoutError(self._message.format(activity=activity))


class Results:
    def __init__(self, error_message: Optional[str],
                 worker_results: Dict[str, Tuple[int, float]]):
        self.error_message = error_message
        self.worker_results = worker_results


class ResultsRecorder:
    """Collects (exit_code, timestamp) per worker of the final generation
    (reference driver.py:44-66)."""

    def __init__(self):
        self._error_message: Optional[str] = None
        self._worker_results: Dict[str, Tuple[int, float]] = {}
        self._threads: "queue.Queue" = queue.Queue()

    def expect(self, worker_thread: threading.Thread) -> None:
        self._threads.put(worker_thread)

    def set_error_message(self, msg: Optional[str]) -> None:
        self._error_message = msg

    def add_result(self, key: str, value: Tuple[int, float]) -> None:
        self._worker_results.setdefault(key, value)

    def get_results(self) -> Results:
        while not self._threads.empty():
            self._threads.get().join()
        return Results(self._error_message, self._worker_results)


class ElasticDriver:
    """Drives elastic membership for one job.

    ``create_worker_fn(slot_info, events) -> (exit_code, timestamp)`` is
    supplied by the launcher (it execs the user command over ssh/local) or
    by tests (a stub). ``events`` are [shutdown_event, host_event]: the
    worker runner should terminate its process when either fires.
    """

    def __init__(self, rendezvous, discovery, min_np: int,
                 max_np: Optional[int] = None,
                 timeout: Optional[float] = None,
                 reset_limit: Optional[int] = None):
        self._rendezvous = rendezvous
        self._discovery = discovery
        self._host_manager = HostManager(discovery)
        self._min_np = min_np
        self._max_np = max_np
        # resolved through the knob registry (HVD_TPU_ELASTIC_TIMEOUT /
        # HOROVOD_ELASTIC_TIMEOUT alias / default) so the launcher-side
        # driver and the documented config table can never disagree
        cfg = _config.Config()
        self._timeout = timeout or float(cfg.get(_config.ELASTIC_TIMEOUT))
        # Policy knobs: growth waits out flapping discovery before a
        # resize; shrink either drains (default) or kills (legacy).
        self._scale_up_delay = float(
            cfg.get(_config.ELASTIC_SCALE_UP_DELAY))
        self._scale_down_policy = str(
            cfg.get(_config.ELASTIC_SCALE_DOWN_POLICY)).strip().lower()
        # Mesh plane: when HVD_TPU_MESH_SHAPE names a parallelism grid,
        # every generation replans it from the survivor count
        # (HVD_TPU_MESH_RESHAPE_POLICY) and publishes the result to the
        # journaled 'mesh' scope for workers to adopt on reset.
        self._mesh_policy = str(
            cfg.get(_config.MESH_RESHAPE_POLICY)).strip().lower()
        self._mesh_config = None
        self._mesh_error: Optional[str] = None
        mesh_spec = str(cfg.get(_config.MESH_SHAPE) or "").strip()
        if mesh_spec:
            from ..parallel import mesh_utils
            self._mesh_config = mesh_utils.mesh_config_from_spec(mesh_spec)
        #: host -> blacklist reason ("failure"/"sdc"/...; "drained" for
        #: graceful departures). Rebuilt from the journaled blacklist
        #: scope on coordinator restart, so re-admission decisions (an
        #: SDC-quarantined host must stay out of a reshaped mesh) never
        #: lose their reason.
        self._blacklist_reasons: Dict[str, str] = {}
        #: host -> {"grace": s, "ts": notice unix time, "start": monotonic}
        #: for in-flight graceful drains (host also flagged in HostManager)
        self._draining: Dict[str, dict] = {}
        #: monotonic time a grow-only membership delta was first seen
        #: (scale-up debounce anchor); None when no growth is pending
        self._scaleup_since: Optional[float] = None

        self._host_assignments: Dict[str, List[SlotInfo]] = {}
        self._rank_assignments: Dict[int, SlotInfo] = {}
        self._world_size = 0

        self._wait_hosts_cond = threading.Condition()
        self._create_worker_fn: Optional[Callable] = None
        self._assignments_callback: Optional[Callable] = None
        self._worker_clients: Dict[Tuple[str, int],
                                   WorkerNotificationClient] = {}

        #: hosts quarantined for SDC this driver lifetime (gauge source;
        #: the durable record is the journaled blacklist scope)
        self._quarantined: set = set()

        self._pending_notice_ts: Optional[float] = None
        self._worker_registry = WorkerStateRegistry(
            self, self._host_manager, reset_limit=reset_limit)
        self._results = ResultsRecorder()
        self._shutdown = threading.Event()

        # Heartbeat liveness: beats observed via the rendezvous PUT handler
        # (elastic/rendezvous.py) feed the monitor; a silent slot past the
        # timeout gets its host event fired, which kills the wedged process
        # and lets the normal exit path drive blacklist + re-rendezvous.
        self._heartbeat_monitor = HeartbeatMonitor(
            on_dead=self._on_heartbeat_timeout)
        self._heartbeat_monitor.start()

        self._discovery_thread = threading.Thread(
            target=self._discover_hosts, name="hvd-elastic-discovery",
            daemon=True)
        self._discovery_thread.start()

    def set_assignments_callback(self, fn: Callable) -> None:
        """``fn(assignment_list)`` runs after each re-assignment has been
        published to the rendezvous — the launcher uses it to publish the
        new generation's JAX coordinator address."""
        self._assignments_callback = fn

    # -- lifecycle ----------------------------------------------------------
    def start(self, np: int, create_worker_fn: Callable) -> None:
        self._create_worker_fn = create_worker_fn
        self._activate_workers(np)

    def resume(self, respawn_all: bool = False) -> None:
        """Form the next generation. ``respawn_all=True`` means every
        process of the previous generation is known dead (peer-death
        cascade), so every slot of the new generation must be spawned —
        not only slots that were previously unassigned."""
        _M_RESETS.inc()
        self._activate_workers(self._min_np, respawn_all=respawn_all)

    def stop(self, error_message: Optional[str] = None) -> None:
        self._results.set_error_message(error_message)
        self._shutdown.set()
        self._heartbeat_monitor.stop()
        with self._wait_hosts_cond:
            self._wait_hosts_cond.notify_all()
        if self._rendezvous is not None:
            self._rendezvous.stop()
        self._discovery_thread.join(timeout=10)

    def finished(self) -> bool:
        return self._shutdown.is_set()

    def get_results(self) -> Results:
        return self._results.get_results()

    # -- worker notification channel -----------------------------------------
    def register_worker_server(self, host: str, slot: int, addresses,
                               secret_key: bytes) -> None:
        self._worker_clients[(host, slot)] = WorkerNotificationClient(
            addresses, secret_key)

    def get_worker_client(self, slot_info: SlotInfo
                          ) -> Optional[WorkerNotificationClient]:
        return self._worker_clients.get(
            (slot_info.hostname, slot_info.local_rank))

    def record_ready(self, host: str, slot: int) -> None:
        self._worker_registry.record_ready(host, slot)

    # -- liveness / blacklist ------------------------------------------------
    def record_heartbeat(self, key: str, value: bytes) -> None:
        """PUT handler for the ``heartbeat`` scope (elastic/rendezvous.py)."""
        host, _, _ = key.rpartition(":")
        if host and self._host_manager.is_draining(host):
            # A draining host's sender may still be beating through its
            # grace window; observing it would re-arm the slot the drain
            # already forgot, and its eventual (expected) silence would
            # then tick the miss counter and fire a spurious timeout.
            return
        self._heartbeat_monitor.observe(key, value)

    def _on_heartbeat_timeout(self, host: str, slot: int, rank) -> None:
        if self.finished() or not self.has_rank_assignment(host, slot):
            return   # already gone: blacklisted, stale generation, shutdown
        # Fire (don't blacklist): the watcher kills the wedged process, its
        # nonzero exit records FAILURE, and the registry blacklists the
        # host on the barrier — one recovery path for every death signal.
        self._host_manager.fire_host_event(host)

    def blacklist_host(self, host: str, reason: str = "failure") -> None:
        """Exclude ``host`` from assignment, by ``reason``:

        * ``"failure"`` (default, and what the registry's barrier uses):
          hard blacklist, persisted to the journaled ``blacklist`` scope so
          a journal-restarted coordinator re-seeds it instead of re-running
          a host it already knows is bad. Permanent for the job.
        * ``"drained"``: graceful departure — the host is excluded from
          new assignments via the *draining* flag, never written to the
          blacklist scope, and re-admitted when its drain completes and
          discovery reports it again.
        """
        if reason == "drained":
            self._blacklist_reasons.setdefault(host, reason)
            self._host_manager.mark_draining(host)
            return
        self._blacklist_reasons[host] = reason
        self._host_manager.blacklist(host)
        try:
            self._rendezvous.put(BLACKLIST_SCOPE, host, reason.encode())
        except Exception:
            log.debug("elastic: could not persist blacklist entry for %s",
                      host, exc_info=True)

    def blacklist_reason(self, host: str) -> Optional[str]:
        """Why ``host`` was excluded (``failure``/``sdc``/``drained``),
        or None if it never was. Survives coordinator restarts via the
        journaled blacklist scope (:meth:`restore_from_rendezvous`)."""
        return self._blacklist_reasons.get(host)

    def record_preemption_notice(self, host: str, grace: float = 0.0,
                                 ts: Optional[float] = None,
                                 persist: bool = True) -> None:
        """One path in for every preemption producer — the ``preempt``
        fault kind (worker PUT), the HTTP ``preempt`` scope, and
        ``HostDiscovery.find_preempted_hosts`` polling all land here.

        Under the default ``drain`` scale-down policy the host is marked
        draining (excluded from the next generation, never blacklisted,
        heartbeat tracking dropped before its beats stop); the discovery
        loop then owes the coordinator a membership notice and the normal
        re-rendezvous retires the host's workers cleanly. ``immediate``
        policy falls back to the legacy kill path (host event -> nonzero
        exit -> FAILURE -> blacklist). Idempotent per in-flight drain.

        ``persist=False`` is used by the rendezvous PUT handler (the
        notice is already in the journaled store) and by journal restore.
        """
        if self.finished() or self._host_manager.is_blacklisted(host):
            return
        if self._scale_down_policy == "immediate":
            if host in self._host_assignments:
                log.warning("elastic: preemption notice for %s; scale-down "
                            "policy 'immediate' kills it now", host)
                _M_PREEMPTIONS.labels(outcome="immediate").inc()
                _M_SCALE_EVENTS.labels(direction="down").inc()
                self._host_manager.fire_host_event(host)
            return
        with self._wait_hosts_cond:
            if self._host_manager.is_draining(host):
                return  # drain already in flight
            log.warning(
                "elastic: preemption notice for %s (grace=%.1fs); draining "
                "gracefully — excluded from new assignments, not "
                "blacklisted, re-admittable when capacity returns",
                host, grace)
            self._host_manager.mark_draining(host)
            self._draining[host] = {
                "grace": float(grace),
                "ts": float(ts) if ts is not None else time.time(),
                "start": time.monotonic()}
            # Forget the host's heartbeat slots BEFORE their beats stop:
            # the armed-then-silent detector must not declare a clean
            # departure dead (record_heartbeat also drops new beats while
            # the drain is in flight, so the slot cannot re-arm).
            for slot_info in self._host_assignments.get(host, []):
                self._heartbeat_monitor.forget(host, slot_info.local_rank)
            _M_SCALE_EVENTS.labels(direction="down").inc()
            self._wait_hosts_cond.notify_all()
        if persist:
            try:
                self._rendezvous.put(PREEMPT_SCOPE, host,
                                     encode_notice(grace, ts))
            except Exception:
                log.debug("elastic: could not persist preemption notice "
                          "for %s", host, exc_info=True)

    def record_sdc_report(self, host: str, kind: str = "nonfinite",
                          strikes: int = 1, ts: Optional[float] = None,
                          persist: bool = True) -> None:
        """One path in for every SDC quarantine producer — the
        worker-side policy's PUT (``send_sdc_report``, routed through
        the rendezvous ``sdc`` scope handler), an operator's HTTP PUT,
        and journal restore all land here.

        The report already encodes the policy verdict (the worker
        counted ``strikes`` locally-attributed detections inside its
        window), so the reaction is immediate: quarantine the host via
        :meth:`blacklist_host` with ``reason='sdc'`` — which persists
        to the journaled blacklist scope, unlike a graceful drain, so a
        flaky chip stays out across coordinator restarts. Idempotent
        per host.

        ``persist=False`` is used by the rendezvous PUT handler (the
        report is already in the journaled store) and by journal
        restore.
        """
        if self.finished():
            return
        if host in self._quarantined or \
                self._host_manager.is_blacklisted(host):
            self._quarantined.add(host)
            _M_QUARANTINED.set(len(self._quarantined))
            return
        log.warning(
            "elastic: SDC quarantine report for %s (kind=%s, strikes=%d) "
            "— blacklisting with reason 'sdc' (persisted: a corrupting "
            "host stays out across restarts)", host, kind, strikes)
        self._quarantined.add(host)
        _M_QUARANTINED.set(len(self._quarantined))
        self.blacklist_host(host, reason="sdc")
        if persist:
            try:
                self._rendezvous.put(SDC_SCOPE, host,
                                     encode_report(kind, strikes, ts))
            except Exception:
                log.debug("elastic: could not persist SDC report for %s",
                          host, exc_info=True)

    def is_draining(self, host: str) -> bool:
        return self._host_manager.is_draining(host)

    def _complete_drain(self, host: str) -> None:
        """The drained host has left the generation: observe the drain
        latency, count the outcome, clear the draining flag (re-admission
        on the next discovery poll) and retire the journaled notice.
        Idempotent (inline reform detection and the poll sweep can race)."""
        if not self._host_manager.is_draining(host):
            return
        info = self._draining.pop(host, None)
        if info is not None:
            _M_DRAIN_SECONDS.observe(time.monotonic() - info["start"])
        _M_PREEMPTIONS.labels(outcome="drained").inc()
        self._host_manager.clear_draining(host)
        log.warning("elastic: drain of %s complete; host is re-admittable "
                    "when discovery reports it again", host)
        try:
            self._rendezvous.delete(PREEMPT_SCOPE, host)
        except Exception:
            log.debug("elastic: could not retire preemption notice for %s",
                      host, exc_info=True)

    def restore_from_rendezvous(self) -> int:
        """Re-seed driver state from a journal-restored KV store: worker
        notification addresses, the blacklist *with reasons*, in-flight
        preemption drains, and the mesh plan. Called by the launcher
        after ``attach_elastic_handlers`` when the rendezvous came back
        from disk (coordinator hot-restart path); a fresh store holds
        nothing and this is a no-op. Returns the number of re-seeded
        entries.

        Reasons matter across a restart that also changes the mesh: an
        SDC-quarantined host must stay quarantined (not degrade to a
        generic failure that a later operator unblacklist would
        re-admit into the reshaped mesh), so the blacklist scope's
        *values* are decoded, not just its keys."""
        import pickle

        count = 0
        for host, blob in self._rendezvous.items(BLACKLIST_SCOPE).items():
            try:
                reason = bytes(blob).decode().strip() or "failure"
            except Exception:
                reason = "failure"
            self._blacklist_reasons.setdefault(host, reason)
            if reason == "sdc" and host not in self._quarantined:
                self._quarantined.add(host)
                _M_QUARANTINED.set(len(self._quarantined))
            if not self._host_manager.is_blacklisted(host):
                self._host_manager.blacklist(host)
                count += 1
        # The mesh plan survives with the blacklist: the restarted
        # coordinator must resume the *reshaped* mesh, not replan from
        # the configured shape as if nothing had been lost.
        mesh_blob = self._rendezvous.items(MESH_SCOPE).get("shape")
        if mesh_blob:
            try:
                from ..parallel import mesh_utils
                axes = json.loads(bytes(mesh_blob).decode()).get("axes", {})
                self._mesh_config = mesh_utils.MeshConfig(**{
                    a: int(v) for a, v in axes.items()
                    if a in mesh_utils.AXIS_ORDER})
                count += 1
            except Exception:
                log.warning("elastic: stale mesh-shape entry not restored",
                            exc_info=True)
        # Drains survive a coordinator restart: the preempt scope is
        # journaled, so a notice recorded before the crash keeps its host
        # out of the restarted coordinator's first generation too.
        for host, blob in self._rendezvous.items(PREEMPT_SCOPE).items():
            if not self._host_manager.is_draining(host):
                grace, ts = decode_notice(blob)
                self.record_preemption_notice(host, grace, ts=ts,
                                              persist=False)
                count += 1
        # SDC quarantines re-seed twice over — the blacklist scope above
        # already re-excluded the host; replaying the sdc scope restores
        # the quarantine bookkeeping (gauge + reason) behind it.
        for host, blob in self._rendezvous.items(SDC_SCOPE).items():
            if host not in self._quarantined:
                kind, strikes, ts = decode_report(blob)
                self.record_sdc_report(host, kind, strikes=strikes, ts=ts,
                                       persist=False)
                count += 1
        for key, blob in self._rendezvous.items(PUT_WORKER_ADDRESSES).items():
            host, _, local_rank = key.rpartition(":")
            try:
                addresses, secret_key = pickle.loads(blob)
                self.register_worker_server(host, int(local_rank),
                                            addresses, secret_key)
                count += 1
            except Exception:
                log.warning("elastic: stale worker-address entry %r not "
                            "restored", key, exc_info=True)
        if count:
            log.warning("elastic: re-seeded %d registry/blacklist entr%s "
                        "from the restored rendezvous", count,
                        "y" if count == 1 else "ies")
        return count

    # -- mesh plane ----------------------------------------------------------
    def mesh_shape(self) -> Optional[Dict[str, int]]:
        """The driver's current mesh plan as axis -> size (None when the
        mesh plane is off, i.e. HVD_TPU_MESH_SHAPE unset)."""
        if self._mesh_config is None:
            return None
        from ..parallel.mesh_utils import AXIS_ORDER
        return {a: int(getattr(self._mesh_config, a)) for a in AXIS_ORDER}

    def mesh_error(self) -> Optional[str]:
        """The last mesh replan failure (MeshShapeError text), cleared by
        the next successful replan. The generation still forms at the old
        shape — a refused replan must be visible, not fatal to the
        control plane."""
        return self._mesh_error

    def _replan_mesh(self, world: int) -> None:
        """Recompute the mesh from the new generation's world size and
        publish it to the journaled ``mesh`` scope. On MeshShapeError
        (survivors don't divide, or policy 'strict' refuses) the old plan
        is kept and the error recorded — the flat-world generation still
        forms, and the operator sees exactly which policy refused which
        counts instead of a pjit shape error."""
        if self._mesh_config is None:
            return
        from ..parallel import mesh_utils
        try:
            plan = mesh_utils.plan_reshape(self._mesh_config, world,
                                           policy=self._mesh_policy)
        except mesh_utils.MeshShapeError as e:
            self._mesh_error = str(e)
            log.error("elastic: mesh replan for world size %d failed: %s "
                      "— keeping the previous mesh plan", world, e)
            return
        self._mesh_error = None
        if plan.direction != "none":
            _M_MESH_RESHAPES.labels(policy=plan.policy,
                                    direction=plan.direction).inc()
            log.warning(
                "elastic: mesh reshaped %s for %d survivor(s): now %s "
                "(policy=%s%s)", plan.direction, world,
                {a: getattr(plan.config, a)
                 for a in mesh_utils.AXIS_ORDER},
                plan.policy,
                f", {plan.dropped} survivor(s) idle" if plan.dropped
                else "")
        self._mesh_config = plan.config
        payload = {
            "axes": {a: int(getattr(plan.config, a))
                     for a in mesh_utils.AXIS_ORDER},
            "policy": plan.policy,
            "dropped": int(plan.dropped),
        }
        try:
            self._rendezvous.put(MESH_SCOPE, "shape",
                                 json.dumps(payload).encode())
        except Exception:
            log.debug("elastic: could not publish mesh shape",
                      exc_info=True)

    # -- assignment queries --------------------------------------------------
    def world_size(self) -> int:
        return self._world_size

    def local_size(self, host: str) -> int:
        return len(self._host_assignments.get(host, []))

    def get_slot_info(self, host: str, slot: int) -> SlotInfo:
        if not self.has_rank_assignment(host, slot):
            return INVALID_SLOT_INFO
        return self._host_assignments[host][slot]

    def get_coordinator_info(self) -> Optional[SlotInfo]:
        return self._rank_assignments.get(0)

    def has_rank_assignment(self, host: str, slot: int) -> bool:
        if self._host_manager.is_blacklisted(host):
            return False
        return host in self._host_assignments \
            and len(self._host_assignments[host]) > slot

    @property
    def host_assignments(self) -> Dict[str, List[SlotInfo]]:
        return self._host_assignments

    # -- internals ----------------------------------------------------------
    def wait_for_available_slots(self, min_np: int,
                                 min_hosts: int = 1) -> DiscoveredHosts:
        tmout = Timeout(
            self._timeout,
            "Timed out waiting for {activity}. Ensure that at least "
            f"{min_np} slots are discoverable.")
        with self._wait_hosts_cond:
            while True:
                current = self._host_manager.current_hosts
                if current.count_available_slots() >= min_np \
                        and len(current.available_hosts) >= min_hosts:
                    return current
                if self._shutdown.is_set():
                    raise RuntimeError(
                        "elastic job has been shut down while waiting for "
                        "available slots")
                self._wait_hosts_cond.wait(min(tmout.remaining(), 1.0))
                tmout.check("minimum number of slots to become available")

    def _activate_workers(self, min_np: int,
                          respawn_all: bool = False) -> None:
        current = self.wait_for_available_slots(min_np)
        pending = self._update_host_assignments(current,
                                                respawn_all=respawn_all)
        self._worker_registry.reset(self.world_size())
        # Liveness restarts with the generation: old beats (and old
        # silences — e.g. a worker that spent the formation re-exec'ing)
        # say nothing about the new membership.
        self._heartbeat_monitor.reset()
        for slot_info in pending:
            self._start_worker_process(slot_info)

    def _discover_hosts(self) -> None:
        first = True
        while not self._shutdown.is_set():
            with self._wait_hosts_cond:
                try:
                    if self._host_manager.update_available_hosts():
                        self._wait_hosts_cond.notify_all()
                except RuntimeError:
                    if first:
                        # Fail fast on a broken discovery script.
                        self._shutdown.set()
                        self._wait_hosts_cond.notify_all()
                        raise
                    log.warning("elastic: discovery failed; retrying",
                                exc_info=True)
            first = False
            # Scheduler-announced reclaims ride the same notice path as
            # the preempt scope and fault kind: poll the discovery
            # object's preemption view each cycle.
            try:
                preempted = self._discovery.find_preempted_hosts()
            except Exception:
                preempted = {}
                log.warning("elastic: preemption discovery failed; "
                            "retrying", exc_info=True)
            for host, grace in (preempted or {}).items():
                self.record_preemption_notice(host, grace)
            self._sweep_completed_drains()
            # Every poll: (re)derive whether a host-change notice is owed
            # and deliver it. Deriving from current state each cycle (not
            # only on a discovery delta) makes the notice self-healing —
            # a notice cleared by a concurrently forming generation, or a
            # delivery that raced worker startup (coordinator service not
            # registered yet), is simply recreated/retried a second later.
            self._refresh_pending_notice()
            self._deliver_pending_notice()
            self._shutdown.wait(DISCOVER_HOSTS_FREQUENCY_SECS)

    def _sweep_completed_drains(self) -> None:
        """A drain is complete once the draining host no longer holds any
        assignment — the re-rendezvous formed a generation without it (the
        common case, also detected inline by ``_update_host_assignments``)
        or it never held one (a spare being reclaimed)."""
        with self._wait_hosts_cond:
            if not self._host_assignments:
                # No generation yet (startup / journal restore): a drain
                # can't be "complete" before the first generation forms
                # without the host.
                return
            done = [h for h in self._host_manager.draining_hosts()
                    if h not in self._host_assignments]
            for host in done:
                self._complete_drain(host)
            if done:
                self._wait_hosts_cond.notify_all()

    def _refresh_pending_notice(self) -> None:
        with self._wait_hosts_cond:
            current = self._host_manager.current_hosts
            next_assignments = {}
            if current.count_available_slots() >= self._min_np:
                next_assignments, _ = self._compute_assignments(current)
            if next_assignments == self.host_assignments:
                # Current generation already reflects the membership.
                self._pending_notice_ts = None
                self._scaleup_since = None
            elif self._pending_notice_ts is None and self._host_assignments:
                if self._is_grow_only(next_assignments):
                    # Pure growth is deliberate, not reactive: wait out
                    # HVD_TPU_ELASTIC_SCALE_UP_DELAY before interrupting
                    # the running generation, so one flapping discovery
                    # poll can't trigger a resize. Any shrink (host lost
                    # or draining) still interrupts immediately.
                    now = time.monotonic()
                    if self._scaleup_since is None:
                        self._scaleup_since = now
                    if now - self._scaleup_since < self._scale_up_delay:
                        return
                self._scaleup_since = None
                self._pending_notice_ts = time.time()

    def _is_grow_only(self, next_assignments: Dict[str, List[SlotInfo]]
                      ) -> bool:
        """True when the pending membership delta only ADDS slots — every
        currently assigned (host, slot) survives into the next layout."""
        prev = {(host, s.local_rank)
                for host, slots in self._host_assignments.items()
                for s in slots}
        new = {(host, s.local_rank)
               for host, slots in next_assignments.items() for s in slots}
        return bool(new - prev) and not (prev - new)

    def _deliver_pending_notice(self) -> None:
        ts = self._pending_notice_ts
        if ts is None:
            return
        coord = self.get_coordinator_info()
        if not coord:
            return
        client = self.get_worker_client(coord)
        if not client:
            return
        try:
            client.notify_hosts_updated(ts)
            self._pending_notice_ts = None
        except Exception:
            log.debug("elastic: failed to notify coordinator of host "
                      "changes; will retry", exc_info=True)

    def _compute_assignments(self, current: DiscoveredHosts):
        host_list = [HostInfo(h, current.get_slots(h))
                     for h in current.host_assignment_order]
        assignment_list, _size = get_host_assignments(
            host_list, self._min_np, self._max_np)
        by_host = defaultdict(list)
        for s in assignment_list:
            by_host[s.hostname].append(s)
        return dict(by_host), assignment_list

    def _update_host_assignments(self, current: DiscoveredHosts,
                                 respawn_all: bool = False
                                 ) -> List[SlotInfo]:
        active = set() if respawn_all else {
            (host, s.local_rank)
            for host, slots in self._host_assignments.items()
            for s in slots}
        by_host, assignment_list = self._compute_assignments(current)
        if self._host_assignments:
            if not (self._host_assignments.keys() & by_host.keys()):
                raise RuntimeError(
                    "no hosts from the previous generation remain; there is "
                    "no surviving rank to broadcast state from")
            # membership delta vs the previous generation (the initial
            # start is not a membership "change")
            prev = {(host, s.local_rank)
                    for host, slots in self._host_assignments.items()
                    for s in slots}
            new = {(host, s.local_rank)
                   for host, slots in by_host.items() for s in slots}
            if new - prev:
                _M_RANK_ADDED.inc(len(new - prev))
                _M_SCALE_EVENTS.labels(direction="up").inc()
            if prev - new:
                _M_RANK_REMOVED.inc(len(prev - new))
        self._host_assignments = by_host
        # Drains complete the moment a generation forms without the host
        # (precise hvd_tpu_elastic_drain_seconds; the 1 Hz sweep is the
        # backstop for hosts that never held an assignment).
        with self._wait_hosts_cond:
            for host in self._host_manager.draining_hosts():
                if host not in by_host:
                    self._complete_drain(host)
        self._world_size = len(assignment_list)
        # Mesh replan BEFORE the rendezvous init: a worker whose blocking
        # rank_and_size GET returns must already be able to read the new
        # generation's mesh shape.
        self._replan_mesh(self._world_size)
        # The generation being formed already reflects current membership;
        # a pending host-change notice would only re-interrupt it.
        self._pending_notice_ts = None
        self._rendezvous.init(assignment_list)
        if self._assignments_callback is not None:
            self._assignments_callback(assignment_list)
        self._rank_assignments = {s.rank: s for s in assignment_list}
        return [s for host, slots in by_host.items() for s in slots
                if (host, s.local_rank) not in active]

    def _start_worker_process(self, slot_info: SlotInfo) -> None:
        create_worker_fn = self._create_worker_fn
        shutdown_event = self._shutdown
        host_event = self._host_manager.get_host_event(slot_info.hostname)

        def run_worker():
            res = create_worker_fn(slot_info, [shutdown_event, host_event])
            exit_code, timestamp = res
            self._handle_worker_exit(slot_info, exit_code, timestamp)

        thread = threading.Thread(target=run_worker, daemon=True,
                                  name=f"hvd-elastic-worker-{slot_info.rank}")
        thread.start()
        self._results.expect(thread)

    def _handle_worker_exit(self, slot_info: SlotInfo, exit_code: int,
                            timestamp: float) -> None:
        # An exited worker's silence is expected; a stale declaration must
        # never fire a host event into a successor generation's worker.
        self._heartbeat_monitor.forget(slot_info.hostname,
                                       slot_info.local_rank)
        if not self.has_rank_assignment(slot_info.hostname,
                                        slot_info.local_rank):
            return  # blacklisted or stale generation
        if exit_code == 0:
            rid = self._worker_registry.record_success(
                slot_info.hostname, slot_info.local_rank)
        else:
            rid = self._worker_registry.record_failure(
                slot_info.hostname, slot_info.local_rank,
                timestamp=timestamp)
        if self.finished() and self._worker_registry.last_rendezvous() == rid:
            name = f"{slot_info.hostname}[{slot_info.local_rank}]"
            self._results.add_result(name, (exit_code, timestamp))
