"""Host discovery and availability tracking for elastic jobs.

Reference: /root/reference/horovod/runner/elastic/discovery.py —
HostDiscoveryScript polls a user script printing ``host[:slots]`` lines
(:131-151), HostManager tracks the discovered set, blacklists failing
hosts, and keeps a *stable* assignment order so surviving hosts keep their
low ranks across membership changes (:79-124).
"""

import subprocess
import threading
from typing import Dict, List, Optional

from .. import faults as _faults

# Chaos site for host discovery: an injected ``error`` behaves exactly
# like a failing discovery script (RuntimeError) — fatal on the first
# poll, logged-and-retried on later ones (driver._discover_hosts).
_FP_DISCOVERY = _faults.FaultPoint("elastic.discovery", exc=RuntimeError)


class HostState:
    """Per-host liveness: an event that fires when the host changes or is
    blacklisted (workers started on that host watch it), plus the blacklist
    flag (reference discovery.py:25-46) and a *draining* flag.

    Draining is deliberately NOT blacklisting: a draining host is excluded
    from new assignments (so the next generation forms without it) but its
    in-flight worker must still be treated as healthy — the registry
    barrier skips blacklisted hosts' READY records, so conflating the two
    would hang the old generation's barrier, and a drained host must stay
    re-admittable once capacity returns."""

    def __init__(self):
        self._event = threading.Event()
        self._blacklisted = False
        self._draining = False

    def get_event(self) -> threading.Event:
        if self._event.is_set():
            # Hand out a fresh event once the old one has fired so a new
            # worker generation can watch this host again.
            self._event = threading.Event()
        return self._event

    def set_event(self) -> None:
        self._event.set()

    def blacklist(self) -> None:
        self._blacklisted = True
        self._event.set()

    def is_blacklisted(self) -> bool:
        return self._blacklisted

    def mark_draining(self) -> None:
        # no set_event(): the draining worker keeps running through its
        # grace window; the re-rendezvous (not a kill) retires it
        self._draining = True

    def clear_draining(self) -> None:
        self._draining = False

    def is_draining(self) -> bool:
        return self._draining


class DiscoveredHosts:
    """Immutable-ish snapshot of the discovered cluster
    (reference discovery.py:49-77)."""

    def __init__(self, host_slots: Dict[str, int],
                 host_assignment_order: List[str]):
        self.host_slots = dict(host_slots)
        self.host_assignment_order = list(host_assignment_order)

    @property
    def available_hosts(self):
        return set(self.host_assignment_order)

    def get_slots(self, host: str) -> int:
        return self.host_slots.get(host, 0)

    def count_available_slots(self) -> int:
        return sum(self.get_slots(h) for h in self.host_assignment_order)

    def drop_blacklisted(self, states: Dict[str, HostState]
                         ) -> "DiscoveredHosts":
        # Draining hosts are dropped alongside blacklisted ones: both are
        # excluded from slot counts and new assignments — but a draining
        # host's state flag is cleared once its drain completes, so it
        # reappears here on the next discovery poll (re-admission).
        self.host_assignment_order = [
            h for h in self.host_assignment_order
            if not (h in states and (states[h].is_blacklisted()
                                     or states[h].is_draining()))]
        return self


class HostDiscovery:
    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        """Return {hostname: slots} for every currently usable host."""
        raise NotImplementedError

    def find_preempted_hosts(self) -> Dict[str, float]:
        """Return {hostname: grace_seconds} for hosts the fleet scheduler
        has announced it will reclaim. Polled by the driver's discovery
        loop each cycle; notices are routed into the same graceful-drain
        path as the ``preempt`` scope and fault kind. Default: none —
        subclasses with a cloud-metadata or scheduler API override this."""
        return {}


class HostDiscoveryScript(HostDiscovery):
    """Runs a user-supplied executable that prints one ``host`` or
    ``host:slots`` per line (reference discovery.py:131-151;
    ``--host-discovery-script``)."""

    def __init__(self, discovery_script: str, default_slots: int = 1):
        self._script = discovery_script
        self._default_slots = default_slots

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        _FP_DISCOVERY.fire()
        proc = subprocess.run(
            self._script, shell=True, capture_output=True, text=True,
            timeout=60)
        if proc.returncode != 0:
            raise RuntimeError(
                f"host discovery script {self._script!r} failed with exit "
                f"code {proc.returncode}: {proc.stderr.strip()}")
        host_slots: Dict[str, int] = {}
        for line in set(proc.stdout.strip().splitlines()):
            line = line.strip()
            if not line:
                continue
            if ":" in line:
                host, slots = line.rsplit(":", 1)
                host_slots[host] = int(slots)
            else:
                host_slots[line] = self._default_slots
        return host_slots


class FixedHosts(HostDiscovery):
    """A mutable fixed host set — the unit-test double the reference uses to
    simulate membership changes without processes
    (reference discovery.py:155-163)."""

    def __init__(self, host_slots: Optional[Dict[str, int]] = None):
        self._host_slots = dict(host_slots or {})

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self._host_slots)

    def set(self, host_slots: Dict[str, int]) -> None:
        self._host_slots = dict(host_slots)


class HostManager:
    """Tracks the discovered host set across polls
    (reference discovery.py:79-124)."""

    def __init__(self, discovery: HostDiscovery):
        self._discovery = discovery
        self._states: Dict[str, HostState] = {}
        self._current = DiscoveredHosts({}, [])

    def _state(self, host: str) -> HostState:
        if host not in self._states:
            self._states[host] = HostState()
        return self._states[host]

    def update_available_hosts(self) -> bool:
        """Poll discovery; returns True when the host set changed. Hosts
        keep their relative order (oldest first) so rank assignments stay
        stable (reference order_available_hosts:113-121)."""
        new_slots = self._discovery.find_available_hosts_and_slots()
        if new_slots == self._current.host_slots:
            return False
        available = [h for h in new_slots
                     if not self._state(h).is_blacklisted()]
        order = [h for h in self._current.host_assignment_order
                 if h in available]
        known = set(order)
        for h in available:
            if h not in known:
                order.append(h)
        # Fire change events for hosts that disappeared.
        for h in self._current.host_slots:
            if h not in new_slots:
                self._state(h).set_event()
        self._current = DiscoveredHosts(new_slots, order)
        return True

    @property
    def current_hosts(self) -> DiscoveredHosts:
        # Filter a fresh snapshot, not the stored one: drop_blacklisted
        # mutates host_assignment_order in place, and a draining host must
        # reappear in the order (same discovery data) once its drain
        # completes and clear_draining runs — an in-place drop would make
        # the exclusion permanent until the host set itself changed.
        snapshot = DiscoveredHosts(self._current.host_slots,
                                   self._current.host_assignment_order)
        return snapshot.drop_blacklisted(self._states)

    def blacklist(self, host: str) -> None:
        self._state(host).blacklist()

    def mark_draining(self, host: str) -> None:
        """Exclude ``host`` from new assignments without blacklisting it
        (graceful preemption drain — see :class:`HostState`)."""
        self._state(host).mark_draining()

    def clear_draining(self, host: str) -> None:
        """Drain finished (or cancelled): the host is re-admittable on the
        next ``current_hosts`` access if discovery still reports it."""
        self._state(host).clear_draining()

    def is_draining(self, host: str) -> bool:
        return host in self._states and self._states[host].is_draining()

    def draining_hosts(self) -> List[str]:
        return [h for h, s in self._states.items() if s.is_draining()]

    def fire_host_event(self, host: str) -> None:
        """Fire the host's change event WITHOUT blacklisting it — how the
        heartbeat monitor kills a silently-wedged worker so its exit flows
        through the normal FAILURE -> blacklist path (a pre-kill blacklist
        would make the registry skip the exit record and hang the
        generation barrier)."""
        self._state(host).set_event()

    def is_blacklisted(self, host: str) -> bool:
        return host in self._states and self._states[host].is_blacklisted()

    def blacklisted_count(self) -> int:
        return sum(1 for s in self._states.values() if s.is_blacklisted())

    def get_host_event(self, host: str) -> threading.Event:
        return self._state(host).get_event()
