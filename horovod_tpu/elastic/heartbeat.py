"""Host-plane heartbeat/liveness layer for elastic jobs.

The reference (and PR 2's hardening) detects worker death two ways: the
launcher's per-worker runner observes the process *exit*, or the JAX
coordination service times out a peer inside a collective. Neither covers
a worker that is silently wedged — process alive, not participating, not
exiting — which otherwise stalls the job until the stall-inspector
shutdown deadline (870s-scale) fires.

This module closes that gap with a third signal that needs no data-plane
cooperation:

* :class:`HeartbeatSender` (worker side) PUTs a per-rank beat to the
  rendezvous KV store (scope ``heartbeat``, key ``hostname:local_rank``)
  every ``HVD_TPU_HEARTBEAT_INTERVAL`` seconds. Beats ride the same
  KV channel as registration, so they also keep the client's coordinator-
  epoch view fresh (a restarted coordinator is noticed within one
  interval, triggering re-registration).
* :class:`HeartbeatMonitor` (driver side) records each beat's *receipt*
  time — launcher clock only, so worker clock skew cannot misdeclare —
  and declares a slot dead after ``HVD_TPU_HEARTBEAT_TIMEOUT`` seconds of
  silence. Declaration fires the host's change event, which kills the
  wedged worker process through the existing watcher, whose nonzero exit
  then drives the normal FAILURE -> blacklist -> re-rendezvous flow. No
  new recovery machinery: the monitor only converts silence into the
  signal the recovery path already understands.

A slot is only armed once its first beat arrives and tracking is cleared
on every generation reset and worker exit, so slow startups, re-execs and
already-recorded failures are never declared dead.

Chaos site ``heartbeat.miss``: fired on the worker's send path; an
injected error suppresses the beat (the wedged-worker simulation the
PR 2 grammar can schedule deterministically).
"""

import logging
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from .. import _locks
from .. import config as _config
from .. import faults as _faults
from .. import metrics as _metrics

log = logging.getLogger("horovod_tpu.elastic")

HEARTBEAT_SCOPE = "heartbeat"

_FP_MISS = _faults.FaultPoint("heartbeat.miss",
                              exc=_faults.InjectedTransientFault)

_M_MISSES = _metrics.counter(
    "hvd_tpu_heartbeat_misses_total",
    "Workers declared dead by the driver's heartbeat monitor (no beat "
    "within HVD_TPU_HEARTBEAT_TIMEOUT), by last-known rank.",
    labels=("rank",))


def heartbeat_key(hostname: str, local_rank) -> str:
    return f"{hostname}:{local_rank}"


class HeartbeatSender:
    """Worker-side beat loop (daemon thread).

    ``client`` is a KVStoreClient; beats are strictly best-effort — a
    failed PUT is skipped, not retried beyond the client's own policy,
    because the next interval is a retry by construction and a beat that
    arrives late is worse than one that is simply missing.
    """

    def __init__(self, client, hostname: str, local_rank, rank,
                 interval: Optional[float] = None):
        self._client = client
        self._key = heartbeat_key(hostname, local_rank)
        self._rank = rank
        self._interval = interval if interval is not None else float(
            _config.Config().get(_config.HEARTBEAT_INTERVAL))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._interval <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="hvd-heartbeat-sender", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=2)

    def beat_once(self) -> bool:
        """One beat; True when it reached the store."""
        try:
            _FP_MISS.fire()
            self._client.put(HEARTBEAT_SCOPE, self._key,
                             str(self._rank).encode())
            return True
        except Exception:
            # includes injected heartbeat.miss faults: a wedged worker
            # doesn't log its own wedging either
            log.debug("elastic: heartbeat for %s not delivered", self._key,
                      exc_info=True)
            return False

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.beat_once()
            self._stop.wait(self._interval)


class HeartbeatMonitor:
    """Driver-side liveness bookkeeping + declaration thread.

    ``on_dead(host, slot, rank)`` runs on the monitor thread when a slot
    armed by a first beat goes silent past the timeout. The driver passes
    a callback that fires the host event (kill -> exit -> FAILURE ->
    blacklist), keeping recovery single-pathed.
    """

    def __init__(self, on_dead: Callable[[str, int, str], None],
                 timeout: Optional[float] = None,
                 poll_interval: Optional[float] = None):
        cfg = _config.Config()
        self._on_dead = on_dead
        self._timeout = timeout if timeout is not None else float(
            cfg.get(_config.HEARTBEAT_TIMEOUT))
        # poll at the beat interval: detection latency is then bounded by
        # timeout + interval < 2 x timeout for any sane interval
        self._poll = poll_interval if poll_interval is not None else max(
            0.1, float(cfg.get(_config.HEARTBEAT_INTERVAL)))
        # A timeout at or below the beat interval would declare perfectly
        # healthy workers dead between beats, thrashing the blacklist
        # until the cluster is exhausted — clamp to 2x the interval so a
        # single dropped beat never kills a worker either.
        floor = 2.0 * self._poll
        if 0 < self._timeout < floor:
            log.warning(
                "elastic: HVD_TPU_HEARTBEAT_TIMEOUT (%.1fs) is below 2x "
                "the heartbeat interval; clamping to %.1fs",
                self._timeout, floor)
            self._timeout = floor
        self._lock = _locks.lock("heartbeat.HeartbeatMonitor._lock")
        #: (host, slot) -> (last receipt monotonic, last reported rank)
        self._beats: Dict[Tuple[str, int], Tuple[float, str]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._timeout <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="hvd-heartbeat-monitor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=2)

    # -- bookkeeping (driver/rendezvous callbacks) ---------------------------
    def observe(self, key: str, value: bytes) -> None:
        """Record a beat's receipt (wired as the ``heartbeat`` scope's PUT
        handler). The key is ``hostname:local_rank``; the value is the
        worker's rank, used only to label the miss counter."""
        host, _, local_rank = key.rpartition(":")
        try:
            slot = int(local_rank)
        except ValueError:
            return
        rank = value.decode(errors="replace") if value else "?"
        with self._lock:
            self._beats[(host, slot)] = (time.monotonic(), rank)

    def forget(self, host: str, slot: int) -> None:
        """Drop a slot (its worker exited — silence is now expected)."""
        with self._lock:
            self._beats.pop((host, slot), None)

    def reset(self) -> None:
        """New generation: nothing already observed still applies."""
        with self._lock:
            self._beats.clear()

    def last_beat_age(self, host: str, slot: int) -> Optional[float]:
        with self._lock:
            entry = self._beats.get((host, slot))
        return None if entry is None else time.monotonic() - entry[0]

    # -- declaration ---------------------------------------------------------
    def check_now(self) -> None:
        """One declaration sweep (the thread loop body; callable directly
        from tests for deterministic timing)."""
        now = time.monotonic()
        with self._lock:
            dead = [(host, slot, rank)
                    for (host, slot), (t, rank) in self._beats.items()
                    if now - t > self._timeout]
            for host, slot, _rank in dead:
                del self._beats[(host, slot)]
        for host, slot, rank in dead:
            _M_MISSES.labels(rank=rank).inc()
            log.warning(
                "elastic: no heartbeat from %s[%s] (rank %s) for more than "
                "%.1fs; declaring it dead and triggering blacklist/"
                "re-rendezvous", host, slot, rank, self._timeout)
            try:
                self._on_dead(host, slot, rank)
            except Exception:
                log.exception("elastic: heartbeat-death handler failed "
                              "for %s[%s]", host, slot)

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._stop.wait(self._poll)
            if self._stop.is_set():
                return
            self.check_now()
