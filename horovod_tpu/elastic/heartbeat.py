"""Host-plane heartbeat/liveness layer for elastic jobs.

The reference (and PR 2's hardening) detects worker death two ways: the
launcher's per-worker runner observes the process *exit*, or the JAX
coordination service times out a peer inside a collective. Neither covers
a worker that is silently wedged — process alive, not participating, not
exiting — which otherwise stalls the job until the stall-inspector
shutdown deadline (870s-scale) fires.

This module closes that gap with a third signal that needs no data-plane
cooperation:

* :class:`HeartbeatSender` (worker side) PUTs a per-rank beat to the
  rendezvous KV store (scope ``heartbeat``, key ``hostname:local_rank``)
  every ``HVD_TPU_HEARTBEAT_INTERVAL`` seconds. Beats ride the same
  KV channel as registration, so they also keep the client's coordinator-
  epoch view fresh (a restarted coordinator is noticed within one
  interval, triggering re-registration).
* :class:`HeartbeatMonitor` (driver side) records each beat's *receipt*
  time — launcher clock only, so worker clock skew cannot misdeclare —
  and declares a slot dead after ``HVD_TPU_HEARTBEAT_TIMEOUT`` seconds of
  silence. Declaration fires the host's change event, which kills the
  wedged worker process through the existing watcher, whose nonzero exit
  then drives the normal FAILURE -> blacklist -> re-rendezvous flow. No
  new recovery machinery: the monitor only converts silence into the
  signal the recovery path already understands.

A slot is only armed once its first beat arrives and tracking is cleared
on every generation reset and worker exit, so slow startups, re-execs and
already-recorded failures are never declared dead.

Chaos site ``heartbeat.miss``: fired on the worker's send path; an
injected error suppresses the beat (the wedged-worker simulation the
PR 2 grammar can schedule deterministically).
"""

import logging
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from .. import _locks
from .. import config as _config
from .. import faults as _faults
from .. import metrics as _metrics

log = logging.getLogger("horovod_tpu.elastic")

HEARTBEAT_SCOPE = "heartbeat"

_FP_MISS = _faults.FaultPoint("heartbeat.miss",
                              exc=_faults.InjectedTransientFault)

_M_MISSES = _metrics.counter(
    "hvd_tpu_heartbeat_misses_total",
    "Workers declared dead by the driver's heartbeat monitor (no beat "
    "within HVD_TPU_HEARTBEAT_TIMEOUT), by last-known rank.",
    labels=("rank",))


def heartbeat_key(hostname: str, local_rank) -> str:
    return f"{hostname}:{local_rank}"


class HeartbeatSender:
    """Worker-side beat loop (daemon thread).

    ``client`` is a KVStoreClient; beats are strictly best-effort — a
    failed PUT is skipped, not retried beyond the client's own policy,
    because the next interval is a retry by construction and a beat that
    arrives late is worse than one that is simply missing.
    """

    def __init__(self, client, hostname: str, local_rank, rank,
                 interval: Optional[float] = None,
                 key: Optional[str] = None):
        self._client = client
        # key= overrides the elastic host:slot scheme — the serving
        # fleet reuses this loop with opaque replica-id keys
        self._key = key if key is not None else heartbeat_key(
            hostname, local_rank)
        self._rank = rank
        self._interval = interval if interval is not None else float(
            _config.Config().get(_config.HEARTBEAT_INTERVAL))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._interval <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="hvd-heartbeat-sender", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=2)

    def beat_once(self) -> bool:
        """One beat; True when it reached the store."""
        try:
            _FP_MISS.fire()
            self._client.put(HEARTBEAT_SCOPE, self._key,
                             str(self._rank).encode())
            return True
        except Exception:
            # includes injected heartbeat.miss faults: a wedged worker
            # doesn't log its own wedging either
            log.debug("elastic: heartbeat for %s not delivered", self._key,
                      exc_info=True)
            return False

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.beat_once()
            self._stop.wait(self._interval)


class LivenessMonitor:
    """Generic armed-then-silent liveness bookkeeping over opaque keys.

    The mechanism the elastic driver trusts — a key is *armed* by its
    first beat, *declared dead* after ``timeout`` seconds of beat
    silence (receipt clock only, so sender clock skew cannot
    misdeclare), declared exactly once, never declared before arming —
    with nothing elastic-specific in it, so the serving fleet's router
    can reuse it for replica liveness with replica-id keys.

    ``on_dead(key, meta)`` runs on the monitor thread (or a direct
    :meth:`check_now` caller) for each declaration; ``meta`` is whatever
    string the last :meth:`observe` recorded. Unlike the elastic flow —
    where death is terminal for the process and re-arming means a fresh
    worker — a declared key is remembered in a dead-set, and when its
    beats *resume* the optional ``on_alive(key)`` callback fires
    (the router's re-admission signal).

    ``timeout`` is clamped to at least 2x ``poll_interval`` so a single
    dropped beat can never declare a healthy sender; detection latency
    is bounded by timeout + poll < 2x timeout.
    """

    def __init__(self, on_dead: Callable[[str, str], None],
                 timeout: float, poll_interval: float,
                 on_alive: Optional[Callable[[str], None]] = None,
                 label: str = "liveness",
                 thread_name: str = "hvd-liveness-monitor"):
        self._on_dead_key = on_dead
        self._on_alive = on_alive
        self._label = label
        self._thread_name = thread_name
        self._timeout = float(timeout)
        self._poll = max(0.05, float(poll_interval))
        # A timeout at or below the beat interval would declare perfectly
        # healthy senders dead between beats — clamp to 2x the interval so
        # a single dropped beat never triggers a declaration either.
        floor = 2.0 * self._poll
        if 0 < self._timeout < floor:
            log.warning(
                "%s: heartbeat timeout (%.1fs) is below 2x the beat "
                "interval; clamping to %.1fs",
                self._label, self._timeout, floor)
            self._timeout = floor
        self._lock = _locks.lock("heartbeat.LivenessMonitor._lock")
        #: key -> (last receipt monotonic, last reported meta)
        self._beats: Dict[str, Tuple[float, str]] = {}
        #: keys declared dead whose next beat is a recovery, not an arming
        self._dead_keys: Dict[str, bool] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def timeout(self) -> float:
        return self._timeout

    def start(self) -> None:
        if self._timeout <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name=self._thread_name, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=2)

    # -- bookkeeping ---------------------------------------------------------
    def observe_key(self, key: str, meta: str = "?") -> None:
        """Record a beat's receipt for ``key``; fires ``on_alive`` when
        the key was previously declared dead."""
        with self._lock:
            self._beats[key] = (time.monotonic(), meta)
            revived = self._dead_keys.pop(key, None) is not None
        if revived and self._on_alive is not None:
            log.info("%s: beats from %s resumed", self._label, key)
            try:
                self._on_alive(key)
            except Exception:
                log.exception("%s: recovery handler failed for %s",
                              self._label, key)

    def forget_key(self, key: str) -> None:
        """Drop a key (its sender left on purpose — silence is now
        expected and a later return is a fresh arming, not a recovery)."""
        with self._lock:
            self._beats.pop(key, None)
            self._dead_keys.pop(key, None)

    def reset(self) -> None:
        """New generation: nothing already observed still applies."""
        with self._lock:
            self._beats.clear()
            self._dead_keys.clear()

    def key_age(self, key: str) -> Optional[float]:
        with self._lock:
            entry = self._beats.get(key)
        return None if entry is None else time.monotonic() - entry[0]

    # -- declaration ---------------------------------------------------------
    def check_now(self) -> None:
        """One declaration sweep (the thread loop body; callable directly
        from tests for deterministic timing)."""
        now = time.monotonic()
        with self._lock:
            dead = [(key, meta) for key, (t, meta) in self._beats.items()
                    if now - t > self._timeout]
            for key, _meta in dead:
                del self._beats[key]
                self._dead_keys[key] = True
        for key, meta in dead:
            try:
                self._declare_dead(key, meta)
            except Exception:
                log.exception("%s: death handler failed for %s",
                              self._label, key)

    def _declare_dead(self, key: str, meta: str) -> None:
        self._on_dead_key(key, meta)

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._stop.wait(self._poll)
            if self._stop.is_set():
                return
            self.check_now()


class HeartbeatMonitor(LivenessMonitor):
    """Driver-side liveness bookkeeping + declaration thread.

    ``on_dead(host, slot, rank)`` runs on the monitor thread when a slot
    armed by a first beat goes silent past the timeout. The driver passes
    a callback that fires the host event (kill -> exit -> FAILURE ->
    blacklist), keeping recovery single-pathed.

    This is the elastic skin over :class:`LivenessMonitor`: keys are
    ``hostname:local_rank``, metadata is the worker's reported rank
    (labels the miss counter), and defaults come from the
    ``HVD_TPU_HEARTBEAT_TIMEOUT`` / ``HVD_TPU_HEARTBEAT_INTERVAL``
    knobs.
    """

    def __init__(self, on_dead: Callable[[str, int, str], None],
                 timeout: Optional[float] = None,
                 poll_interval: Optional[float] = None):
        cfg = _config.Config()
        self._on_dead = on_dead
        if timeout is None:
            timeout = float(cfg.get(_config.HEARTBEAT_TIMEOUT))
        # poll at the beat interval: detection latency is then bounded by
        # timeout + interval < 2 x timeout for any sane interval
        if poll_interval is None:
            poll_interval = max(
                0.1, float(cfg.get(_config.HEARTBEAT_INTERVAL)))
        super().__init__(on_dead=self._unused_, timeout=timeout,
                         poll_interval=poll_interval, label="elastic",
                         thread_name="hvd-heartbeat-monitor")

    @staticmethod
    def _unused_(key: str, meta: str) -> None:  # _declare_dead overrides
        raise AssertionError("unreachable")

    # -- bookkeeping (driver/rendezvous callbacks) ---------------------------
    def observe(self, key: str, value: bytes) -> None:
        """Record a beat's receipt (wired as the ``heartbeat`` scope's PUT
        handler). The key is ``hostname:local_rank``; the value is the
        worker's rank, used only to label the miss counter."""
        host, _, local_rank = key.rpartition(":")
        try:
            int(local_rank)
        except ValueError:
            return
        rank = value.decode(errors="replace") if value else "?"
        self.observe_key(heartbeat_key(host, int(local_rank)), meta=rank)

    def forget(self, host: str, slot: int) -> None:
        """Drop a slot (its worker exited — silence is now expected)."""
        self.forget_key(heartbeat_key(host, slot))

    def last_beat_age(self, host: str, slot: int) -> Optional[float]:
        return self.key_age(heartbeat_key(host, slot))

    # -- declaration ---------------------------------------------------------
    def _declare_dead(self, key: str, meta: str) -> None:
        host, _, local_rank = key.rpartition(":")
        slot, rank = int(local_rank), meta
        _M_MISSES.labels(rank=rank).inc()
        log.warning(
            "elastic: no heartbeat from %s[%s] (rank %s) for more than "
            "%.1fs; declaring it dead and triggering blacklist/"
            "re-rendezvous", host, slot, rank, self._timeout)
        self._on_dead(host, slot, rank)
