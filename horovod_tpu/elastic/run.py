"""Worker-side elastic retry loop and world reset.

Reference: /root/reference/horovod/common/elastic.py run_fn:147-168 (the
sync -> train -> catch -> restore/reset loop) and torch/elastic.py:46-49
(reset = ``hvd.shutdown(); hvd.init()``).

**TPU-native reset design.** The reference can re-rendezvous Gloo/NCCL
inside a living process. XLA cannot: ``jax.distributed.initialize`` must
run before the first backend use, so a worker that survives a membership
change cannot rebuild the distributed runtime in-process — and on real
hardware a changed TPU slice topology forces a runtime restart anyway.
The reset therefore:

1. re-queries the launcher's rendezvous for this worker's new rank/size
   (one *blocking* GET of ``rank_and_size/hostname:local_rank`` — the
   driver holds the request until the new generation has fully formed,
   reference gloo/gloo_context.cc:157-170 + elastic/rendezvous.py:29-60)
   and the new generation's coordinator address (scope ``coordinator``);
2. persists the state's committed snapshot (already host-side numpy after
   ``save()``) to a local file;
3. **re-execs the worker process** with the refreshed env. The restarted
   script reaches ``@hvd.elastic.run`` again, which reloads the snapshot
   before ``state.sync()``; newly added workers skip the reload and
   receive state through the rank-0 broadcast in ``sync()``.

Anything not stored in the State object does not survive a reset — the
same contract as any checkpoint/restore system, and in practice the same
contract as the reference (only State is restored there too).

Outside an elastic launch (no ``HVD_TPU_ELASTIC``), reset falls back to
in-process ``shutdown(); init()``, which is valid whenever the JAX
distributed runtime is not (re)needed — e.g. unit tests and single-process
development loops.
"""

import functools
import logging
import os
import pickle
import sys
import tempfile

from .. import config as _config
from .. import metrics as _metrics
from ..exceptions import HorovodInternalError, HostsUpdatedInterrupt
from .worker import notification_manager

log = logging.getLogger("horovod_tpu.elastic")

_M_RESTARTS = _metrics.counter(
    "hvd_tpu_worker_restarts_total",
    "Elastic worker resets taken by this process (re-exec into a new "
    "generation, or in-process shutdown+init outside elastic launches).")

RANK_ENV = ("HVD_TPU_RANK", "HVD_TPU_SIZE", "HVD_TPU_LOCAL_RANK",
            "HVD_TPU_LOCAL_SIZE", "HVD_TPU_CROSS_RANK", "HVD_TPU_CROSS_SIZE")
RESTART_STATE_ENV = "HVD_TPU_RESTART_STATE_FILE"
#: Job-scoped directory (set by the elastic launcher) where every commit()
#: persists the committed snapshot. A worker hard-killed by the runtime
#: (e.g. the JAX coordination service fatally terminating survivors of a
#: peer death) cannot run the graceful pre-exec persistence path below, so
#: durability must be paid at commit time — the same contract as the
#: reference, where the survivor's in-memory committed state survives
#: because the survivor process itself survives (common/elastic.py:60-101).
STATE_DIR_ENV = "HVD_TPU_ELASTIC_STATE_DIR"


def _rendezvous_client(timeout: float = 24 * 3600.0):
    from ..runner.rendezvous import KVStoreClient
    addr = os.environ.get("HVD_TPU_RENDEZVOUS_ADDR")
    if not addr:
        return None
    port = int(os.environ.get("HVD_TPU_RENDEZVOUS_PORT", 0))
    # The rank_and_size GET blocks server-side until the next generation
    # forms, so the client timeout must cover the elastic timeout.
    return KVStoreClient(addr, port, timeout=timeout)


def requery_assignment() -> bool:
    """Refresh this worker's rank env vars from the rendezvous.

    Returns False when this worker has no slot in the new generation (its
    host was removed) — the caller should exit cleanly. No-op (True) in
    non-elastic runs.
    """
    client = _rendezvous_client()
    if client is None:
        return True
    hostname = os.environ.get("HVD_TPU_HOSTNAME", "")
    local_rank = os.environ.get("HVD_TPU_LOCAL_RANK", "0")
    blob = client.get("rank_and_size", f"{hostname}:{local_rank}")
    if blob is None:
        raise HorovodInternalError(
            "rendezvous did not return a rank assignment")
    fields = [int(x) for x in blob.decode().split(",")]
    if fields[0] < 0:
        return False
    for env_name, value in zip(RANK_ENV, fields):
        os.environ[env_name] = str(value)
    coord = client.get("coordinator", "addr")
    if coord:
        os.environ["HVD_TPU_COORDINATOR_ADDR"] = coord.decode()
    return True


def fetch_mesh_shape() -> "dict | None":
    """The driver's published mesh plan (axis -> size), or None.

    Workers call this after :func:`requery_assignment` (or at startup)
    to learn the mesh the new generation should re-form — the driver's
    :meth:`ElasticDriver._replan_mesh` publishes it to the journaled
    ``mesh`` scope *before* the blocking rank_and_size GET returns, so
    a worker that has its new rank can always read the matching shape.
    None outside elastic launches, when the mesh plane is off
    (``HVD_TPU_MESH_SHAPE`` unset), or on any fetch failure — callers
    fall back to their local mesh construction.
    """
    client = _rendezvous_client(timeout=5.0)
    if client is None:
        return None
    try:
        blob = client.get("mesh", "shape")
    except Exception:
        return None
    if not blob:
        return None
    import json
    try:
        axes = json.loads(blob.decode()).get("axes")
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(axes, dict):
        return None
    try:
        return {str(a): int(v) for a, v in axes.items()}
    except (TypeError, ValueError):
        return None


def _persist_state(state) -> None:
    """Write the committed snapshot next to the env for the exec'd self."""
    saved = getattr(state, "_saved_state", None)
    if saved is None:
        return
    fd, path = tempfile.mkstemp(prefix="hvd_tpu_elastic_state_",
                                suffix=".pkl")
    with os.fdopen(fd, "wb") as f:
        pickle.dump(saved, f)
    os.environ[RESTART_STATE_ENV] = path


def committed_state_path() -> "str | None":
    """This worker's durable commit file, or None outside elastic launches.

    The filename carries the launcher's job id so a reused (e.g. shared-
    storage) state dir can never hand a new job a previous job's final
    state.
    """
    d = os.environ.get(STATE_DIR_ENV)
    if not d:
        return None
    import socket
    hostname = os.environ.get("HVD_TPU_HOSTNAME") or socket.gethostname()
    local_rank = os.environ.get("HVD_TPU_LOCAL_RANK", "0")
    job = os.environ.get("HVD_TPU_ELASTIC_JOB_ID", "job")
    return os.path.join(d, f"state_{job}_{hostname}_{local_rank}.pkl")


def persist_committed_state(state) -> None:
    """Durably persist the committed snapshot (called from State.commit()).

    Atomic write+rename so a kill mid-commit leaves the previous commit
    intact. Strictly best-effort: persistence failures (unwritable dir,
    unpicklable user attribute, full disk) must never turn a commit that
    used to succeed into a training crash — recovery then degrades to the
    rank-0 broadcast, exactly the pre-durability behavior. No-op outside
    elastic launches (no STATE_DIR_ENV) or when
    HVD_TPU_ELASTIC_DURABLE_COMMITS=0 (opt-out for huge states committed
    every batch, where the synchronous pickle+write would dominate step
    time).
    """
    if not _config.Config().get(_config.ELASTIC_DURABLE_COMMITS):
        return
    path = committed_state_path()
    if not path:
        return
    saved = getattr(state, "_saved_state", None)
    if saved is None:
        return
    try:
        # Remote hosts may not have the launcher-created dir; best-effort
        # local persistence still covers same-host respawns. The
        # checkpointing layout helper gives tmp+fsync+rename, so a kill
        # mid-commit can never leave a torn state file (plain rename
        # without the fsync could surface an empty file after a host
        # crash — the exact window durable commits exist to close).
        from ..checkpointing.layout import atomic_write_bytes
        os.makedirs(os.path.dirname(path), exist_ok=True)
        atomic_write_bytes(path, pickle.dumps(saved))
    except Exception:  # noqa: BLE001 — durability is best-effort by contract
        log.warning("elastic: failed to persist committed state to %s",
                    path, exc_info=True)


def maybe_load_persisted_state(state) -> bool:
    """Reload a persisted snapshot into ``state``.

    Two sources, in priority order:
    1. the pre-exec snapshot file (graceful re-exec reset path);
    2. this slot's durable commit file (driver-respawned workers whose
       predecessor was hard-killed by the runtime).
    Brand-new workers have neither and get state from the rank-0 broadcast
    in ``state.sync()``.
    """
    path = os.environ.pop(RESTART_STATE_ENV, None)
    if path and os.path.exists(path):
        try:
            with open(path, "rb") as f:
                saved = pickle.load(f)
            if hasattr(state, "_saved_state"):
                state._saved_state = saved
                state.restore()
                return True
            return False
        finally:
            try:
                os.unlink(path)
            except OSError:
                pass
    commit_path = committed_state_path()
    if commit_path and os.path.exists(commit_path):
        try:
            with open(commit_path, "rb") as f:
                saved = pickle.load(f)
        except (OSError, pickle.UnpicklingError):
            log.warning("elastic: could not reload committed state from %s",
                        commit_path, exc_info=True)
            return False
        if hasattr(state, "_saved_state"):
            log.info("elastic: restored committed state from %s", commit_path)
            state._saved_state = saved
            state.restore()
            return True
    return False


def reset(state=None) -> None:
    """Tear down the world and come back up on the new membership."""
    from .. import basics
    # Counted before the re-exec branch: the counter must tick while this
    # process can still tick it (the exec'd image starts a fresh registry,
    # but scrape/snapshot readers see the increment between reset start
    # and exec).
    _M_RESTARTS.inc()
    # Async checkpoint saves must land (or fail visibly) before this
    # process image goes away: a re-exec with a snapshot still queued
    # would silently drop the newest checkpoint. On a preemption drain
    # this IS the departing host's final flush — the in-flight sharded
    # save completes before the process exits, so the survivors' restore
    # sees the full pre-notice progress.
    from ..checkpointing import drain_all
    drain_all()
    basics.shutdown()
    if not requery_assignment():
        # No slot in the new generation: this host was removed — either
        # reclaimed after a preemption drain or simply scaled away. Leave
        # the last committed snapshot durably on disk (the survivors'
        # broadcast path and a later re-admitted worker both read it) and
        # retire the notification plane so the driver never sees this exit
        # as anything but clean.
        if state is not None:
            persist_committed_state(state)
        notification_manager.shutdown()
        log.info("elastic: this worker has no assignment in the new "
                 "generation; drain complete, exiting cleanly")
        sys.exit(0)
    if os.environ.get("HVD_TPU_ELASTIC") == "1":
        # XLA backends cannot re-rendezvous in-process: restart the worker
        # image with the refreshed env (see module docstring).
        if state is not None:
            _persist_state(state)
        notification_manager.shutdown()
        log.info("elastic: re-exec'ing worker for new generation "
                 "(rank=%s size=%s)", os.environ.get("HVD_TPU_RANK"),
                 os.environ.get("HVD_TPU_SIZE"))
        sys.stdout.flush()
        sys.stderr.flush()
        os.execve(sys.executable, [sys.executable] + sys.argv,
                  dict(os.environ))
    else:
        basics.init()


def run_fn(func, reset_fn):
    """Wrap ``func(state, ...)`` in the elastic retry loop
    (reference common/elastic.py:147-168). ``reset_fn(state)`` re-forms
    the world between attempts."""

    @functools.wraps(func)
    def wrapper(state, *args, **kwargs):
        notification_manager.init()
        notification_manager.register_listener(state)
        maybe_load_persisted_state(state)
        try:
            while True:
                state.sync()
                try:
                    return func(state, *args, **kwargs)
                except HorovodInternalError:
                    log.warning("elastic: caught HorovodInternalError; "
                                "restoring committed state", exc_info=True)
                    state.restore()
                except HostsUpdatedInterrupt:
                    log.info("elastic: hosts updated; re-initializing")
                reset_fn(state)
                state.on_reset()
        finally:
            notification_manager.remove_listener(state)

    return wrapper


def run(func):
    """Decorator for elastic training functions::

        @hvd.elastic.run
        def train(state, ...):
            ...
    """
    return run_fn(func, reset)
