"""TensorFlow interop: TF2 eager training with the TPU-hosted collective
plane.

Reference surface: horovod/tensorflow (/root/reference/horovod/tensorflow/
__init__.py — ``allreduce`` :52-131, ``DistributedGradientTape`` :465-518,
``broadcast_variables`` in functions.py) re-exported process queries, and
the broadcast hook. TF tensors bridge through **DLPack** in both
directions — zero-copy for CPU-resident eager tensors
(``np.from_dlpack`` on the TF tensor's ``__dlpack__``;
``tf.experimental.dlpack.from_dlpack`` on results) — with host numpy as
the fallback, the same staging contract as :mod:`horovod_tpu.torch`
(reference adapters: tensorflow/mpi_ops.cc TFTensor; CPU staging
torch/mpi_ops_v2.cc:92+). TF in this stack is CPU-resident while jax owns
the TPU.

Usage (reference's TF2 recipe)::

    import horovod_tpu.tensorflow as hvd
    hvd.init()
    with tf.GradientTape() as tape:
        loss = loss_fn(model(x))
    tape = hvd.DistributedGradientTape(tape)
    grads = tape.gradient(loss, model.trainable_variables)
    opt.apply_gradients(zip(grads, model.trainable_variables))
    if first_batch:
        hvd.broadcast_variables(model.variables, root_rank=0)
"""

from typing import Any, List, Optional

import numpy as np

from .. import basics as _basics
from .. import collectives as _c
from ..basics import (  # noqa: F401  (reference API parity re-exports)
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size,
)
from ..collectives import Average, Sum, Adasum  # noqa: F401


def _tf():
    import tensorflow as tf
    return tf


def _to_numpy(t) -> np.ndarray:
    """tf tensor/Variable -> numpy. Zero-copy via DLPack when the tensor is
    CPU-resident and exposes ``__dlpack__`` (TF >= 2.13); ``.numpy()``
    otherwise (itself often copy-free for CPU eager tensors)."""
    if isinstance(t, np.ndarray):
        return t
    src = getattr(t, "value", None)
    src = src() if callable(src) else t   # Variables: read the live tensor
    try:
        return np.from_dlpack(src)
    except Exception:
        return src.numpy() if hasattr(src, "numpy") else np.asarray(src)


def _from_result(out, dtype=None):
    """jax result -> tf tensor: DLPack import (zero-copy for CPU-backed jax
    arrays; the result buffer is exclusively ours once the collective
    finished) with a numpy-copy fallback."""
    tf = _tf()
    try:
        t = tf.experimental.dlpack.from_dlpack(out.__dlpack__())
    except Exception:
        t = tf.convert_to_tensor(np.asarray(out))
    if dtype is not None and t.dtype != dtype:
        t = tf.cast(t, dtype)
    return t


def _resolve_compression(compression):
    if compression is None:
        from ..compression import Compression
        return Compression.none
    return compression


def allreduce(tensor, average=None, *, name: Optional[str] = None, op=None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              compression=None, device_dense: str = "",
              device_sparse: str = ""):
    """Allreduce of a tf.Tensor (reference: tensorflow/__init__.py:52-131).
    tf.IndexedSlices take the gather path (reference :87-102).
    ``compression`` compresses the wire payload (numpy boundary, applied
    inside the gradient-recording closure so gradients still flow).
    ``device_dense``/``device_sparse`` are accepted for reference API
    parity and ignored: data-plane placement belongs to XLA here, not to
    tf.device scopes. Everything past ``average`` is KEYWORD-ONLY — the
    reference's positional tail differs (its third positional is
    ``device_dense``, this plane has ``name``), so a positional
    reference-style call raises at the call site instead of silently
    misbinding a device string as a collective name."""
    tf = _tf()
    del device_dense, device_sparse
    compression = _resolve_compression(compression)
    if isinstance(tensor, tf.IndexedSlices):
        from ..sparse import SparseGradient, allreduce_sparse
        avg = op is None and (average is None or average) or op == Average
        out = allreduce_sparse(
            SparseGradient(indices=_to_numpy(tensor.indices),
                           values=_to_numpy(tensor.values),
                           dense_shape=tuple(tensor.dense_shape.numpy())),
            average=bool(avg), name=name)
        return tf.IndexedSlices(
            values=_from_result(np.asarray(out.values)),
            indices=_from_result(np.asarray(out.indices)),
            dense_shape=tensor.dense_shape)
    # Differentiable (reference: RegisterGradient("HorovodAllreduce"),
    # tensorflow/mpi_ops.py — the gradient of an allreduce is the same
    # allreduce of the upstream gradient). tf.custom_gradient records the
    # grad fn on the tape in eager mode; inside tf.function use the
    # DistributedGradientTape / optimizer wrappers, which route through a
    # py_function submission point instead.
    op_r = _c._resolve_op(average, op)

    @tf.custom_gradient
    def _differentiable(x):
        payload, cc = compression.compress(_to_numpy(x))
        out = _c.allreduce(payload, op=op_r, name=name,
                           prescale_factor=prescale_factor,
                           postscale_factor=postscale_factor)
        out = _from_result(compression.decompress(out, cc), x.dtype)

        def grad(dy):
            gp, gcc = compression.compress(_to_numpy(dy))
            g = _c.allreduce(gp, op=op_r,
                             prescale_factor=prescale_factor,
                             postscale_factor=postscale_factor)
            return _from_result(compression.decompress(g, gcc), dy.dtype)
        return out, grad
    return _differentiable(tensor)


def allgather(tensor, name: Optional[str] = None):
    """Differentiable allgather (reference gradient: sum-allreduce of the
    upstream gradient, narrowed to this process's rows —
    RegisterGradient("HorovodAllgather"), tensorflow/mpi_ops.py). The
    backward math is shared with the torch bridge
    (functions.allgather_grad_numpy)."""
    tf = _tf()
    from ..functions import allgather_grad_numpy
    if not hasattr(tensor, "dtype"):
        tensor = np.asarray(tensor)   # plain sequences/scalars
    shape = getattr(tensor, "shape", None)
    # tf shapes expose .rank (None when unknown); numpy arrays/scalars
    # go through np.shape
    if hasattr(shape, "rank"):
        nd = shape.rank
    else:
        shape = np.shape(tensor)
        nd = len(shape)
    if nd is None:
        raise ValueError(
            "allgather requires a statically known rank (the gradient "
            "narrows this process's rows by its static dim0); got a "
            "tensor of unknown rank")
    dim0 = int(shape[0]) if nd else 1

    @tf.custom_gradient
    def _differentiable(x):
        out = _from_result(_c.allgather(_to_numpy(x), name=name), x.dtype)

        def grad(dy):
            return _from_result(
                allgather_grad_numpy(_to_numpy(dy), dim0,
                                     was_scalar=nd == 0), dy.dtype)
        return out, grad
    return _differentiable(tensor)


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    """Differentiable broadcast (reference gradient: sum-allreduce
    delivered to the root, zero elsewhere —
    RegisterGradient("HorovodBroadcast"), tensorflow/mpi_ops.py)."""
    tf = _tf()
    from ..functions import broadcast_grad_numpy

    @tf.custom_gradient
    def _differentiable(x):
        out = _from_result(
            _c.broadcast(_to_numpy(x), root_rank=root_rank, name=name),
            x.dtype)

        def grad(dy):
            return _from_result(
                broadcast_grad_numpy(_to_numpy(dy), root_rank), dy.dtype)
        return out, grad
    return _differentiable(tensor)


def alltoall(tensor, splits=None, name: Optional[str] = None):
    out = _c.alltoall(_to_numpy(tensor), splits=splits, name=name)
    return _from_result(out, tensor.dtype)


# async verbs (handles interchangeable with horovod_tpu.collectives)
def allreduce_async(tensor, average=None, name: Optional[str] = None,
                    op=None) -> int:
    return _c.allreduce_async(_to_numpy(tensor), average=average, name=name,
                              op=op)


def allgather_async(tensor, name: Optional[str] = None) -> int:
    return _c.allgather_async(_to_numpy(tensor), name=name)


def broadcast_async(tensor, root_rank: int,
                    name: Optional[str] = None) -> int:
    return _c.broadcast_async(_to_numpy(tensor), root_rank=root_rank,
                              name=name)


def alltoall_async(tensor, splits=None, name: Optional[str] = None) -> int:
    return _c.alltoall_async(_to_numpy(tensor), splits=splits, name=name)


def synchronize(handle: int):
    return _from_result(_c.synchronize(handle))


poll = _c.poll


def broadcast_variables(variables: List, root_rank: int = 0) -> None:
    """Assign every variable its root-rank value (reference:
    tensorflow/functions.py broadcast_variables). Order is the caller's
    list order, identical across processes by construction.

    Fused: variables are bucketed to the fusion threshold and each bucket
    rides ONE grouped broadcast dispatch — not one collective per variable
    (reference fusion-buffer broadcasts, collective_operations.cc:37-81)."""
    from .. import config as _config
    from ..fusion import plan_buckets
    vars_ = list(variables)
    if not vars_:
        return
    staged = [_to_numpy(v) for v in vars_]
    try:
        threshold = int(
            _basics.world().config.get(_config.FUSION_THRESHOLD))
    except Exception:
        threshold = 64 * 1024 * 1024
    buckets = plan_buckets(
        [(a.shape, a.dtype) for a in staged], threshold)
    for bi, idxs in enumerate(buckets):
        outs = _c.grouped_broadcast(
            [staged[i] for i in idxs], root_rank=root_rank,
            name=f"bcast.vars.{bi}.{len(idxs)}")
        for i, out in zip(idxs, outs):
            vars_[i].assign(np.asarray(out))


def broadcast_object(obj: Any, root_rank: int = 0,
                     name: Optional[str] = None):
    from ..functions import broadcast_object as _bo
    return _bo(obj, root_rank=root_rank, name=name)


def _reduce_gradients(grads, op, name_prefix: str,
                      prescale: float = 1.0, postscale: float = 1.0,
                      compression=None):
    """Reduce a list of TF gradients (None entries pass through).

    Eager tensors reduce directly. Inside a tf.function (Keras 3 traces
    train_step), the whole list reduces through ONE ``tf.py_function`` node
    running the fused eager grouped_allreduce — a single graph-side
    submission point, so every process issues the identical collective
    sequence regardless of TF's graph scheduling (the ordering guarantee
    the reference gets from its background negotiation thread), and the
    gradients fuse like the reference's fusion buffer. ``compression``
    compresses the wire payloads (numpy boundary).
    """
    tf = _tf()
    compression = _resolve_compression(compression)
    present = [(i, g) for i, g in enumerate(grads) if g is not None]
    if not present:
        return list(grads)
    dense = [
        (i, tf.convert_to_tensor(g) if isinstance(g, tf.IndexedSlices)
         else g)
        for i, g in present]

    def _eager_reduce(*tensors):
        pairs = [compression.compress(np.asarray(t)) for t in tensors]
        outs = _c.grouped_allreduce(
            [c for c, _ in pairs], op=op,
            name=name_prefix + ".grads",
            prescale_factor=prescale, postscale_factor=postscale)
        return [np.asarray(compression.decompress(o, cc))
                for o, (_, cc) in zip(outs, pairs)]

    symbolic = any(not hasattr(g, "numpy") for _, g in dense)
    tensors = [g for _, g in dense]
    if symbolic:
        reduced = tf.py_function(
            func=lambda *ts: _eager_reduce(*[t.numpy() for t in ts]),
            inp=tensors, Tout=[g.dtype for g in tensors])
        for r, (_, g) in zip(reduced, dense):
            r.set_shape(g.shape)
    else:
        reduced = [tf.convert_to_tensor(o, dtype=g.dtype)
                   for o, (_, g) in zip(_eager_reduce(*tensors), dense)]
    out = list(grads)
    for (i, _), r in zip(dense, reduced):
        out[i] = r
    return out


class DistributedGradientTape:
    """Wraps a tf.GradientTape so ``gradient()`` returns allreduced
    gradients (reference: tensorflow/__init__.py:465-518)."""

    def __init__(self, tape, op=Average, compression=None,
                 prescale_factor: float = 1.0,
                 postscale_factor: float = 1.0):
        self._tape = tape
        self._op = op
        self._compression = compression
        self._prescale = prescale_factor
        self._postscale = postscale_factor

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources, output_gradients)
        return _reduce_gradients(grads, self._op, "tape",
                                 self._prescale, self._postscale,
                                 compression=self._compression)

    def __getattr__(self, item):
        return getattr(self._tape, item)


def _make_v1_distributed_optimizer(optimizer, op, name_prefix, compression,
                                   prescale_factor, postscale_factor):
    """TF1 graph-mode wrapper (reference: tensorflow/__init__.py:259-301
    _DistributedOptimizer): subclasses ``tf.compat.v1.train.Optimizer`` and
    overrides ``compute_gradients`` so legacy session scripts — including
    ``minimize()`` and estimator trains — get reduced gradients. The
    collective enters the graph through ``_reduce_gradients``' single
    ``tf.py_function`` node (one submission point per step, fused), the
    graph-mode analogue of the reference's HorovodAllreduceOp kernels."""
    tf = _tf()

    class _DistributedOptimizerV1(tf.compat.v1.train.Optimizer):
        def __init__(self):
            self._optimizer = optimizer
            super().__init__(use_locking=False,
                             name=name_prefix or "DistributedOptimizerV1")

        def compute_gradients(self, *args, **kwargs):
            gvs = self._optimizer.compute_gradients(*args, **kwargs)
            reduced = _reduce_gradients(
                [g for g, _ in gvs], op, name_prefix,
                prescale_factor, postscale_factor, compression=compression)
            return [(r, v) for r, (_, v) in zip(reduced, gvs)]

        def apply_gradients(self, *args, **kwargs):
            return self._optimizer.apply_gradients(*args, **kwargs)

        def get_slot(self, *args, **kwargs):
            return self._optimizer.get_slot(*args, **kwargs)

        def get_slot_names(self, *args, **kwargs):
            return self._optimizer.get_slot_names(*args, **kwargs)

        def variables(self, *args, **kwargs):
            return self._optimizer.variables(*args, **kwargs)

    return _DistributedOptimizerV1()


def DistributedOptimizer(optimizer, op=Average, name_prefix: str = "opt",
                         compression=None, prescale_factor: float = 1.0,
                         postscale_factor: float = 1.0):
    """Wrap an optimizer so gradients are reduced before being applied.

    A ``tf.compat.v1.train.Optimizer`` (legacy graph scripts) gets the
    reference's subclassing treatment — ``compute_gradients`` reduces
    (reference: tensorflow/__init__.py:259-301 _DistributedOptimizer). A
    keras/TF2 optimizer is intercepted at ``apply_gradients`` (with Keras 3
    that is the only stable hook). ``compression`` compresses the wire
    payloads."""
    tf = _tf()
    if isinstance(optimizer, tf.compat.v1.train.Optimizer):
        return _make_v1_distributed_optimizer(
            optimizer, op, name_prefix, compression,
            prescale_factor, postscale_factor)

    def apply_gradients(grads_and_vars, *args, **kwargs):
        gv = list(grads_and_vars)
        reduced = _reduce_gradients([g for g, _ in gv], op, name_prefix,
                                    prescale_factor, postscale_factor,
                                    compression=compression)
        return type(optimizer).apply_gradients(
            optimizer, [(r, v) for r, (_, v) in zip(reduced, gv)],
            *args, **kwargs)

    optimizer.apply_gradients = apply_gradients
    return optimizer


def DistributedDeltaOptimizer(optimizer, backward_passes_per_step: int = 1,
                              name_prefix: str = "adasum_delta"):
    """Adasum *delta* optimizer (reference: tensorflow/__init__.py:303-397
    _DistributedAdasumOptimizer): the inner optimizer updates variables
    locally; on each communication step the scale-invariant Adasum rule
    combines the accumulated model *deltas* (var - start) across processes
    and every variable is set to start + adasum(delta).

    The reference builds this as a TF1 graph optimizer with ``delta_start``
    slots and tf.cond step gating; here the same algorithm runs eagerly
    (TF2/Keras-3), with the start snapshots held as non-trainable variables.
    """
    tf = _tf()
    state = {"starts": {}, "step": 0}
    orig_apply = type(optimizer).apply_gradients

    def apply_gradients(grads_and_vars, *args, **kwargs):
        gv = list(grads_and_vars)
        vars_ = [v for _, v in gv]
        # initialize start snapshots on the first step (delta_start slots)
        for v in vars_:
            if v.ref() not in state["starts"]:
                state["starts"][v.ref()] = tf.Variable(v, trainable=False)
        result = orig_apply(optimizer, gv, *args, **kwargs)
        state["step"] += 1
        if state["step"] % backward_passes_per_step == 0:
            deltas = [(v - state["starts"][v.ref()]).numpy() for v in vars_]
            reduced = _c.grouped_allreduce(
                deltas, op=_c.Adasum,
                name=f"{name_prefix}.{state['step']}")
            for v, rd in zip(vars_, reduced):
                start = state["starts"][v.ref()]
                start.assign_add(np.asarray(rd))
                v.assign(start)
        return result

    optimizer.apply_gradients = apply_gradients
    return optimizer


class BroadcastGlobalVariablesHook:
    """TF1 ``SessionRunHook`` that broadcasts all global variables from the
    root rank after session creation (reference:
    tensorflow/__init__.py:187-220). Construct lazily on top of
    ``tf.compat.v1.train.SessionRunHook`` so graph-mode users get consistent
    initialization; in TF2 eager code use :func:`broadcast_variables`.

    The graph side only carries placeholder-fed assigns; the broadcast itself
    runs through the eager XLA collective plane on host values — the same
    host-staging contract as the rest of this module.
    """

    def __new__(cls, root_rank: int = 0, device: str = ""):
        tf = _tf()

        class _Hook(tf.compat.v1.train.SessionRunHook):
            def __init__(self):
                self.root_rank = root_rank
                self._vars = None
                self._phs = None
                self._assign = None

            def begin(self):
                self._vars = tf.compat.v1.global_variables()
                self._phs = [
                    tf.compat.v1.placeholder(v.dtype.base_dtype, v.shape)
                    for v in self._vars]
                self._assign = tf.group(*[
                    tf.compat.v1.assign(v, p)
                    for v, p in zip(self._vars, self._phs)])

            def after_create_session(self, session, coord):
                vals = session.run(self._vars)
                outs = [np.asarray(_c.broadcast(
                    np.asarray(val), root_rank=self.root_rank,
                    name=f"bcast.gv.{i}"))
                    for i, val in enumerate(vals)]
                session.run(self._assign,
                            feed_dict=dict(zip(self._phs, outs)))

        return _Hook()


def __getattr__(name):  # PEP 562: keep tensorflow import deferred
    if name == "elastic":
        import importlib
        return importlib.import_module(".elastic", __name__)
    if name == "Compression":
        from ..compression import Compression
        return Compression
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
