"""Elastic state for TensorFlow/Keras models.

Reference: /root/reference/horovod/tensorflow/elastic.py:91-210 —
``TensorFlowKerasState`` (model + optimizer weight snapshots, rank-0 sync)
and ``TensorFlowState`` (raw variable lists). Snapshots live in host numpy
(device buffers do not survive a mesh re-initialization), and ``sync``
re-seeds restarted workers by broadcasting rank 0's live values.
"""

from typing import List, Optional

import numpy as np

from .. import collectives as _c
from ..elastic.run import run, run_fn  # noqa: F401  (reference re-export)
from ..elastic.state import ObjectState


def _bcast_arrays(arrays: List[np.ndarray], prefix: str) -> List[np.ndarray]:
    return [np.asarray(_c.broadcast(a, root_rank=0, name=f"{prefix}.{i}"))
            for i, a in enumerate(arrays)]


class TensorFlowKerasState(ObjectState):
    """Elastic state wrapping a Keras model (+ optimizer) plus plain attrs
    (reference: tensorflow/elastic.py TensorFlowKerasState).

    Usage::

        state = hvd.elastic.TensorFlowKerasState(model, optimizer, batch=0)

        @hvd.elastic.run
        def train(state):
            model.fit(..., callbacks=[hvd.elastic.CommitStateCallback(state)])
    """

    def __init__(self, model, optimizer=None, **kwargs):
        self.model = model
        self.optimizer = optimizer if optimizer is not None else getattr(
            model, "optimizer", None)
        self._saved_weights = [np.array(w) for w in model.get_weights()]
        self._saved_opt_weights = self._opt_values()
        bcast_object = kwargs.pop("bcast_object", None)
        get_rank = kwargs.pop("get_rank", None)
        super().__init__(bcast_object=bcast_object, get_rank=get_rank,
                         **kwargs)

    def _opt_vars(self):
        opt = self.optimizer
        if opt is None:
            return []
        # Keras 3 exposes .variables; legacy optimizers expose .weights
        return list(getattr(opt, "variables", None)
                    or getattr(opt, "weights", []) or [])

    def _opt_values(self):
        return [np.array(v.numpy()) for v in self._opt_vars()]

    def save(self) -> None:
        self._saved_weights = [np.array(w) for w in self.model.get_weights()]
        self._saved_opt_weights = self._opt_values()
        super().save()

    def restore(self) -> None:
        self.model.set_weights([w.copy() for w in self._saved_weights])
        for v, w in zip(self._opt_vars(), self._saved_opt_weights):
            v.assign(w)
        super().restore()

    def sync(self) -> None:
        weights = _bcast_arrays(
            [np.array(w) for w in self.model.get_weights()],
            "elastic.keras.w")
        self.model.set_weights(weights)
        opt_vals = _bcast_arrays(self._opt_values(), "elastic.keras.opt")
        for v, w in zip(self._opt_vars(), opt_vals):
            v.assign(w)
        self._saved_weights = [w.copy() for w in weights]
        self._saved_opt_weights = [w.copy() for w in opt_vals]
        super().sync()


# The Keras-facing name (reference: horovod/_keras/elastic.py KerasState)
KerasState = TensorFlowKerasState


class TensorFlowState(ObjectState):
    """Elastic state for a raw list of tf.Variables (reference:
    tensorflow/elastic.py TensorFlowState)."""

    def __init__(self, variables: Optional[List] = None, **kwargs):
        if variables is None:
            import tensorflow as tf
            variables = tf.compat.v1.global_variables()
        self.variables = list(variables)
        self._saved_values = [np.array(v.numpy()) for v in self.variables]
        bcast_object = kwargs.pop("bcast_object", None)
        get_rank = kwargs.pop("get_rank", None)
        super().__init__(bcast_object=bcast_object, get_rank=get_rank,
                         **kwargs)

    def save(self) -> None:
        self._saved_values = [np.array(v.numpy()) for v in self.variables]
        super().save()

    def restore(self) -> None:
        for v, val in zip(self.variables, self._saved_values):
            v.assign(val)
        super().restore()

    def sync(self) -> None:
        vals = _bcast_arrays(
            [np.array(v.numpy()) for v in self.variables], "elastic.tf.v")
        for v, val in zip(self.variables, vals):
            v.assign(val)
        self._saved_values = [v.copy() for v in vals]
        super().sync()
