"""``horovod_tpu.tensorflow.keras`` — the reference's primary TF2 Keras
entry point (``import horovod.tensorflow.keras as hvd``; reference
``horovod/tensorflow/keras/__init__.py`` wraps the same shared ``_keras``
implementation as ``horovod.keras``). Identical surface to
:mod:`horovod_tpu.keras`; both route through the TF bridge.
"""

from ..keras import (  # noqa: F401
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    Average, Sum, Adasum,
    DistributedOptimizer, allreduce, allgather, broadcast,
    broadcast_variables, callbacks, load_model,
)
from . import elastic  # noqa: F401  (KerasState + elastic callbacks)
