"""Pallas TPU kernels for hot ops.

The reference's hot path is hand-written CUDA/NCCL
(/root/reference/horovod/common/ops/); the TPU build's hot paths are XLA
collectives plus Pallas kernels for the ops XLA doesn't schedule optimally.
"""

from .flash_attention import (  # noqa: F401
    flash_attention, flash_attention_with_lse, mha_reference,
    use_pallas_default,
)
