"""Flash (blockwise online-softmax) attention as a Pallas TPU kernel.

No counterpart exists in the reference (it is a communication framework;
SURVEY.md §2.3), but the TPU build's long-context strategies — ring
attention over 'sp' (parallel/ring_attention.py) and Ulysses head sharding
(parallel/ulysses.py) — need an attention inner loop that never
materializes the (S_q, S_k) score matrix in HBM. This kernel computes exact
attention with fp32 online-softmax accumulators, tiled (block_q x block_k)
so the MXU sees dense (block, D) matmuls and HBM traffic stays O(S*D).

Positions are global: ``q_offset``/``k_offset`` give the global index of
local row 0, so a shard_map caller can mask causally across device shards
(ring attention passes the rotating source block's offset each step). They
are *dynamic* values (traced under shard_map — e.g. derived from
``jax.lax.axis_index``) and ride into the kernel through SMEM, which keeps
one compiled kernel serving every ring step.

The public entry is differentiable via custom_vjp: the forward saves the
per-row log-sum-exp and the backward recomputes scores blockwise (the
standard flash-attention recipe) in plain XLA, so memory stays O(S*D) end
to end while the forward rides the Pallas kernel.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30

# Bound lazily so this module imports on machines without pallas support.
pl = None
pltpu = None


def _ensure_pallas():
    global pl, pltpu
    if pl is None:
        from jax.experimental import pallas as _pl
        from jax.experimental.pallas import tpu as _pltpu
        pl, pltpu = _pl, _pltpu


def use_pallas_default() -> bool:
    """Pallas kernels compile only for TPU; elsewhere the interpreter (or
    the XLA reference path) runs — mirrors how the reference picks NCCL on
    GPU and Gloo on CPU (operations.cc:142-233 ordered dispatch)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Reference implementation (test oracle + non-TPU fallback)
# ---------------------------------------------------------------------------

def mha_reference(q, k, v, causal: bool = True,
                  sm_scale: Optional[float] = None,
                  q_offset=0, k_offset=0, out_dtype=None):
    """Exact attention in plain XLA. Shapes (B, S, H, D); fp32 softmax."""
    out_dtype = out_dtype or q.dtype
    D = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(D)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = k_offset + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(out_dtype)


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                sm_scale, causal, block_k, sk_real, block_q):
    """One (batch*head, q-block) program: stream K/V blocks with the
    online-softmax recurrence.

    Refs: q (1, block_q, D); k, v (1, S_k_padded, D); o (1, block_q, D);
    lse (1, 1, S_q) — per-row log-sum-exp residual for the backward. The lse
    block spans the full row (TPU tiling forbids a (1, block_q) block) and
    stays resident across this batch-head's q-block programs; each program
    stores its slice.
    """
    iq = pl.program_id(1)
    D = q_ref.shape[-1]
    q = q_ref[0]                                         # (bq, D) native dtype
    sk_pad = k_ref.shape[1]
    nkb = sk_pad // block_k

    qpos = (qoff_ref[0, 0] + iq * block_q
            + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0))

    def body(j, carry):
        o, m, l = carry
        # inputs stay in their storage dtype (bf16 feeds the MXU at full
        # rate); accumulation is fp32 via preferred_element_type
        kb = k_ref[0, pl.ds(j * block_k, block_k), :]
        vb = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # (bq, bk)
        col = (j * block_k
               + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1))
        valid = col < sk_real                            # mask padded K rows
        if causal:
            kpos = koff_ref[0, 0] + col
            valid = jnp.logical_and(valid, qpos >= kpos)
        s = jnp.where(valid, s, NEG_INF)
        m_blk = jnp.max(s, axis=-1, keepdims=True)       # (bq, 1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bq, D)
        o_new = o * corr + pv
        return o_new, m_new, l_new

    o0 = jnp.zeros((block_q, D), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)

    if causal:
        # Skip key blocks entirely above the diagonal: key block j is needed
        # iff its first key position <= this program's last query position.
        q_last = qoff_ref[0, 0] + (iq + 1) * block_q - 1
        nkb_needed = jnp.clip(
            (q_last - koff_ref[0, 0]) // block_k + 1, 0, nkb)
    else:
        nkb_needed = nkb
    o, m, l = jax.lax.fori_loop(0, nkb_needed, body, (o0, m0, l0))

    l = jnp.maximum(l, 1e-30)                            # fully-masked rows
    o_ref[0] = (o / l).astype(o_ref.dtype)
    lse_ref[0, 0, pl.ds(iq * block_q, block_q)] = m[:, 0] + jnp.log(l[:, 0])


@functools.partial(
    jax.jit, static_argnames=("causal", "sm_scale", "block_q", "block_k",
                              "sk_real", "interpret", "vma"))
def _flash_fwd(q, k, v, q_offset, k_offset, *, causal, sm_scale,
               block_q, block_k, sk_real, interpret, vma=None):
    """(BH, S_q, D) x (BH, S_k_padded, D) -> out (BH, S_q, D),
    lse (BH, S_q). S_q % block_q == 0, S_k_padded % block_k == 0."""
    _ensure_pallas()
    BH, SQ, D = q.shape
    SK = k.shape[1]
    grid = (BH, SQ // block_q)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_k=block_k,
        sk_real=sk_real, block_q=block_q)
    qoff = q_offset.reshape(1, 1)
    koff = k_offset.reshape(1, 1)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda b, i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda b, i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, SK, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, SK, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, SQ), lambda b, i: (b, 0, 0)),
        ],
        out_shape=[
            # vma: under shard_map the outputs vary over the caller's mesh
            # axes (ring attention's 'sp'); None outside shard_map
            jax.ShapeDtypeStruct((BH, SQ, D), q.dtype,
                                 vma=frozenset(vma) if vma else None),
            jax.ShapeDtypeStruct((BH, 1, SQ), jnp.float32,
                                 vma=frozenset(vma) if vma else None),
        ],
        compiler_params=None if interpret else pltpu.CompilerParams(
            # bh programs are independent; q-block programs share the
            # resident lse row block, so that dimension stays sequential
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(qoff, koff, q, k, v)
    return out, lse[:, 0, :]


# ---------------------------------------------------------------------------
# Differentiable entry point. Offsets are float32 scalars (differentiable
# dtype with zero cotangent) so traced values — axis_index-derived ring
# positions — flow through custom_vjp.
# ---------------------------------------------------------------------------

def _pad_to(x, axis, mult):
    pad = (-x.shape[axis]) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash(q, k, v, qoff, koff, causal, sm_scale, block_q, block_k,
           interpret, vma):
    """Returns (out, lse). lse (the per-row log-sum-exp of scores) is a
    first-class differentiable output: ring attention merges per-step block
    results through it, so its cotangent feeds the score gradients."""
    return _flash_fwd_padded(q, k, v, qoff, koff, causal, sm_scale,
                             block_q, block_k, interpret, vma)


def _flash_fwd_padded(q, k, v, qoff, koff, causal, sm_scale, block_q,
                      block_k, interpret, vma=None):
    sq = q.shape[1]
    sk = k.shape[1]
    out, lse = _flash_fwd(
        _pad_to(q, 1, block_q), _pad_to(k, 1, block_k),
        _pad_to(v, 1, block_k), qoff, koff, causal=causal,
        sm_scale=sm_scale, block_q=block_q, block_k=block_k, sk_real=sk,
        interpret=interpret, vma=vma)
    return out[:, :sq], lse[:, :sq]


def _flash_vjp_fwd(q, k, v, qoff, koff, causal, sm_scale, block_q, block_k,
                   interpret, vma):
    out, lse = _flash_fwd_padded(q, k, v, qoff, koff, causal, sm_scale,
                                 block_q, block_k, interpret, vma)
    return (out, lse), (q, k, v, qoff, koff, out, lse)


def _flash_vjp_bwd(causal, sm_scale, block_q, block_k, interpret, vma, res,
                   gs):
    """Blockwise recompute backward (standard flash-attention bwd) in XLA:
    memory stays O(S*D + S*block) via a scan over K blocks. The lse
    cotangent g_lse enters the score gradient as
    d lse / d s_k = softmax_k = exp(s_k - lse)."""
    g, g_lse = gs
    q, k, v, qoff, koff, out, lse = res
    BH, SQ, D = q.shape
    SK = k.shape[1]
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    g = g.astype(jnp.float32)
    g_lse = g_lse.astype(jnp.float32)
    delta = jnp.sum(out.astype(jnp.float32) * g, axis=-1)  # (BH, SQ)
    qpos = qoff + jnp.arange(SQ)
    koff_i = koff

    nkb = -(-SK // block_k)
    kfp = _pad_to(kf, 1, block_k)
    vfp = _pad_to(vf, 1, block_k)

    def kblock(dq_acc, j):
        ks = jax.lax.dynamic_slice_in_dim(kfp, j * block_k, block_k, 1)
        vs = jax.lax.dynamic_slice_in_dim(vfp, j * block_k, block_k, 1)
        s = jnp.einsum("bqd,bkd->bqk", qf, ks) * sm_scale
        col = j * block_k + jnp.arange(block_k)
        valid = col[None, :] < SK
        if causal:
            valid = valid & (qpos[:, None] >= (koff_i + col)[None, :])
        s = jnp.where(valid[None], s, NEG_INF)
        p = jnp.exp(s - lse[..., None])                    # (BH, SQ, bk)
        dp = jnp.einsum("bqd,bkd->bqk", g, vs)
        ds = p * (dp - delta[..., None] + g_lse[..., None]) * sm_scale
        dq_acc = dq_acc + jnp.einsum("bqk,bkd->bqd", ds, ks)
        dk_blk = jnp.einsum("bqk,bqd->bkd", ds, qf)
        dv_blk = jnp.einsum("bqk,bqd->bkd", p, g)
        return dq_acc, (dk_blk, dv_blk)

    dq0 = jnp.zeros((BH, SQ, D), jnp.float32)
    if vma:
        # under shard_map the carry must be marked varying over the caller's
        # mesh axes to match the body output's vma
        dq0 = jax.lax.pcast(dq0, tuple(vma), to="varying")
    dq, (dk_blocks, dv_blocks) = jax.lax.scan(kblock, dq0, jnp.arange(nkb))
    dk = jnp.moveaxis(dk_blocks, 0, 1).reshape(BH, nkb * block_k, D)[:, :SK]
    dv = jnp.moveaxis(dv_blocks, 0, 1).reshape(BH, nkb * block_k, D)[:, :SK]
    # integer offsets have float0 cotangents
    zero_off = np.zeros(res[3].shape, jax.dtypes.float0)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            zero_off, zero_off)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention_with_lse(q, k, v, causal: bool = True,
                             sm_scale: Optional[float] = None,
                             q_offset=0, k_offset=0,
                             block_q: int = 512, block_k: int = 128,
                             interpret: Optional[bool] = None,
                             out_dtype=None, vma=None):
    """Flash attention over (B, S, H, D) tensors; also returns the per-row
    log-sum-exp ``lse`` with shape (B, S, H) — differentiable — so callers
    can merge partial attention over distributed K/V blocks (ring
    attention's per-step combine).

    On TPU this runs the Pallas kernel; elsewhere (or with
    ``interpret=True`` for testing) the kernel runs interpreted.
    ``q_offset``/``k_offset`` are the global positions of local row 0 for
    causal masking across sharded sequences; they may be traced values
    (ring attention derives them from ``jax.lax.axis_index``).
    """
    out_dtype = out_dtype or q.dtype
    B, SQ, H, D = q.shape
    SK = k.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(D))
    if interpret is None:
        interpret = not use_pallas_default()
    block_q = min(block_q, SQ)
    block_k = min(block_k, SK)

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)
    out, lse = _flash(to_bh(q), to_bh(k), to_bh(v),
                      jnp.asarray(q_offset, jnp.int32),
                      jnp.asarray(k_offset, jnp.int32),
                      causal, float(sm_scale), int(block_q), int(block_k),
                      bool(interpret), tuple(vma) if vma else None)
    out = out.reshape(B, H, SQ, D).transpose(0, 2, 1, 3)
    lse = lse.reshape(B, H, SQ).transpose(0, 2, 1)
    return out.astype(out_dtype), lse


def flash_attention(q, k, v, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    q_offset=0, k_offset=0,
                    block_q: int = 512, block_k: int = 128,
                    interpret: Optional[bool] = None,
                    out_dtype=None, vma=None):
    """Flash attention over (B, S, H, D); see flash_attention_with_lse."""
    out, _ = flash_attention_with_lse(
        q, k, v, causal=causal, sm_scale=sm_scale, q_offset=q_offset,
        k_offset=k_offset, block_q=block_q, block_k=block_k,
        interpret=interpret, out_dtype=out_dtype, vma=vma)
    return out
