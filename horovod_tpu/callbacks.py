"""Training-loop callbacks for distributed runs.

Reference surface: the Keras callback family
(/root/reference/horovod/_keras/callbacks.py:22-190 —
BroadcastGlobalVariablesCallback, MetricAverageCallback,
LearningRateScheduleCallback, LearningRateWarmupCallback). TPU-native
redesign: there is no Keras model object mutating an optimizer variable, so
callbacks operate on an explicit :class:`TrainingRun` record that the user's
loop threads through the hooks — params pytree in, params pytree out, and a
``lr_scale`` the loop multiplies into its learning rate (compose with optax
via :func:`scaled_schedule`). Hook protocol and semantics (staircase vs
continuous schedules, warmup formula, averaging metric logs in place) match
the reference.

Typical loop::

    run = hvd.callbacks.TrainingRun(params=params, steps_per_epoch=spe)
    cbs = hvd.callbacks.CallbackList(
        [hvd.callbacks.BroadcastGlobalVariablesCallback(0),
         hvd.callbacks.LearningRateWarmupCallback(warmup_epochs=5),
         hvd.callbacks.MetricAverageCallback()], run)
    cbs.on_train_begin()
    for epoch in range(E):
        cbs.on_epoch_begin(epoch)
        for batch in range(spe):
            cbs.on_batch_begin(batch)
            params, opt_state, logs = step(run.params, opt_state,
                                           lr_scale=run.lr_scale)
            run.params = params
            cbs.on_batch_end(batch, logs)
            # NOTE: always train on run.params (re-read after the hooks):
            # BroadcastGlobalVariablesCallback rewrites it at batch 0
        cbs.on_epoch_end(epoch, logs)
    cbs.on_train_end(logs)   # drains async checkpoint saves, etc.
"""

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np


@dataclass
class TrainingRun:
    """Mutable record the callbacks read and write."""
    params: Any = None                  # model pytree (broadcast target)
    steps_per_epoch: Optional[int] = None
    lr_scale: float = 1.0               # multiplied into the loop's LR
    epoch: int = 0
    extra_state: Dict[str, Any] = field(default_factory=dict)


class Callback:
    run: TrainingRun = None  # set by CallbackList

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch: int, logs=None):
        pass

    def on_batch_begin(self, batch: int, logs=None):
        pass

    def on_batch_end(self, batch: int, logs=None):
        pass

    def on_epoch_end(self, epoch: int, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks: List[Callback], run: TrainingRun):
        self.callbacks = list(callbacks)
        self.run = run
        for cb in self.callbacks:
            cb.run = run

    def __iter__(self):
        return iter(self.callbacks)

    def _fire(self, hook, *args, **kw):
        for cb in self.callbacks:
            getattr(cb, hook)(*args, **kw)

    def on_train_begin(self, logs=None):
        self._fire("on_train_begin", logs)

    def on_train_end(self, logs=None):
        # fired by the loop after the last epoch; async checkpoint
        # callbacks drain their in-flight saves here
        self._fire("on_train_end", logs)

    def on_epoch_begin(self, epoch, logs=None):
        self.run.epoch = epoch
        self._fire("on_epoch_begin", epoch, logs)

    def on_batch_begin(self, batch, logs=None):
        self._fire("on_batch_begin", batch, logs)

    def on_batch_end(self, batch, logs=None):
        self._fire("on_batch_end", batch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._fire("on_epoch_end", epoch, logs)


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast ``run.params`` from ``root_rank`` once, at the start of
    training (reference: _keras/callbacks.py:22-46 — broadcast on first
    batch so late-restored checkpoints win)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank
        self._done = False

    def on_batch_end(self, batch, logs=None):
        if self._done:
            return
        from .functions import broadcast_parameters
        self.run.params = broadcast_parameters(
            self.run.params, root_rank=self.root_rank)
        self._done = True


def average_logs(logs, name_prefix: str = "metric") -> None:
    """Average numeric scalar entries of ``logs`` across processes, in
    place, in sorted-name order so every process submits the same
    collective sequence (reference: _keras/callbacks.py:48-87). Shared by
    the flax-loop and Keras MetricAverageCallback variants."""
    if not logs:
        return
    from . import collectives as _c
    for metric in sorted(logs):
        value = logs[metric]
        if isinstance(value, bool) or not (
                isinstance(value, (int, float, np.floating, np.integer))
                or (hasattr(value, "shape") and np.ndim(value) == 0)):
            continue
        out = _c.allreduce(np.asarray(value, np.float64), op=_c.Average,
                           name=f"{name_prefix}.{metric}")
        logs[metric] = float(np.asarray(out))


class MetricAverageCallback(Callback):
    """Average the epoch-end metric logs across processes in place
    (reference: _keras/callbacks.py:48-87)."""

    def on_epoch_end(self, epoch, logs=None):
        average_logs(logs, "metric")


class LearningRateScheduleCallback(Callback):
    """Scale the loop's LR by ``multiplier(epoch)`` within
    [start_epoch, end_epoch) (reference: _keras/callbacks.py:90-166).
    ``staircase`` updates once per epoch; otherwise the epoch is fractional
    per batch (needs ``run.steps_per_epoch``)."""

    def __init__(self, multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True):
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        if not callable(multiplier):
            self.staircase = True
            self.multiplier: Callable[[float], float] = lambda e: multiplier
        else:
            self.staircase = staircase
            self.multiplier = multiplier

    def on_batch_begin(self, batch, logs=None):
        epoch = self.run.epoch
        if epoch < self.start_epoch or (
                self.end_epoch is not None and epoch >= self.end_epoch):
            return
        if self.staircase:
            if batch == 0:
                self.run.lr_scale = float(self.multiplier(epoch))
        else:
            spe = self.run.steps_per_epoch
            if not spe:
                raise ValueError(
                    "non-staircase schedules need TrainingRun."
                    "steps_per_epoch (reference: _autodetect_steps_per_epoch)")
            self.run.lr_scale = float(self.multiplier(epoch + batch / spe))

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs["lr_scale"] = self.run.lr_scale


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual warmup from 1x to dp_size()x LR over ``warmup_epochs``
    (reference: _keras/callbacks.py:169-190, formula from Goyal et al.
    "Accurate, Large Minibatch SGD"). The scale starts near 1/size (so
    base_lr * size * scale ~ base_lr) and reaches 1."""

    def __init__(self, warmup_epochs: float = 5, verbose: int = 0,
                 size: Optional[int] = None):
        self._size = size
        self.verbose = verbose

        def multiplier(epoch):
            n = self._world_size()
            epoch += 1.0 / (self.run.steps_per_epoch or 1)
            return 1.0 / n * (epoch * (n - 1) / warmup_epochs + 1)
        super().__init__(multiplier, start_epoch=0, end_epoch=warmup_epochs,
                         staircase=False)

    def _world_size(self) -> int:
        if self._size is not None:
            return self._size
        from . import basics
        return basics.dp_size() if basics.is_initialized() else 1

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if self.verbose and epoch == (self.end_epoch or 0) - 1:
            import logging
            logging.getLogger("horovod_tpu").info(
                "Epoch %d: finished gradual learning rate warmup to scale "
                "%.4f.", epoch + 1, self.run.lr_scale)


def scaled_schedule(base_schedule, run: TrainingRun):
    """Wrap an optax schedule (or constant) so callback LR scaling applies:
    ``lr(step) = base(step) * run.lr_scale``. The scale is read at call
    time, so pass the resulting schedule via optax.inject_hyperparams or
    rebuild the optimizer per epoch when running fully jitted."""
    def schedule(count):
        base = base_schedule(count) if callable(base_schedule) \
            else base_schedule
        return base * run.lr_scale
    return schedule
