"""Lazy native build: compile csrc/*.cc into _libhvdtpu.so with the system
C++ toolchain on first use.

The reference ships its native core through setup.py CMake extensions built
at pip-install time (/root/reference/setup.py). Here the library is small and
dependency-free, so it is built on demand next to the sources, keyed by a
content hash — a fresh checkout self-builds on first import, and editing a
.cc transparently rebuilds. Set HVD_TPU_NATIVE=0 to skip native entirely
(pure-Python fallbacks cover every component).
"""

import hashlib
import os
import subprocess
import tempfile

_HERE = os.path.dirname(os.path.abspath(__file__))
CSRC = os.path.join(_HERE, "csrc")
LIB_BASENAME = "_libhvdtpu.so"


def _sources():
    return sorted(
        os.path.join(CSRC, f) for f in os.listdir(CSRC)
        if f.endswith((".cc", ".hpp")))


def _content_hash() -> str:
    h = hashlib.sha256()
    for path in _sources():
        h.update(os.path.basename(path).encode())
        with open(path, "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def lib_path() -> str:
    return os.path.join(_HERE, LIB_BASENAME)


def _stamp_path() -> str:
    return lib_path() + ".stamp"


def build(force: bool = False) -> str:
    """Build (or reuse) the shared library; returns its path.

    Raises RuntimeError when no working C++ toolchain is available — callers
    fall back to pure Python.
    """
    want = _content_hash()
    lib = lib_path()
    stamp = _stamp_path()
    if not force and os.path.exists(lib) and os.path.exists(stamp):
        with open(stamp) as f:
            if f.read().strip() == want:
                return lib

    cxx = os.environ.get("CXX", "g++")
    srcs = [s for s in _sources() if s.endswith(".cc")]
    # Compile into a temp file then atomically rename, so a concurrent
    # process never dlopens a half-written .so.
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_HERE)
    os.close(fd)
    cmd = [cxx, "-O2", "-std=c++17", "-fPIC", "-shared", "-pthread",
           "-fvisibility=hidden", "-o", tmp] + srcs
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    except (OSError, subprocess.TimeoutExpired) as e:
        os.unlink(tmp)
        raise RuntimeError(f"native build failed to run {cxx}: {e}") from e
    if proc.returncode != 0:
        os.unlink(tmp)
        raise RuntimeError(
            f"native build failed:\n{proc.stderr[-4000:]}")
    os.replace(tmp, lib)
    with open(stamp, "w") as f:
        f.write(want)
    return lib
