"""ctypes bindings for the horovod_tpu native host runtime.

The native library carries the host-plane components the reference implements
in C++ (/root/reference/horovod/common/): submission table (tensor_queue),
response cache, fusion planner (controller.cc FuseResponses), stall
inspector, timeline writer, wire format (message.{h,cc}) and the autotuner's
GP/Bayesian optimizer (optim/). ``get()`` returns the loaded bindings or
``None`` — every consumer has a pure-Python fallback, so a machine without a
C++ toolchain (or with HVD_TPU_NATIVE=0) loses nothing but host-path speed.
"""

import ctypes
import os
import threading
from typing import Optional

_lock = threading.Lock()
_lib = None
_tried = False


class _Bindings:
    def __init__(self, cdll: ctypes.CDLL):
        self.cdll = cdll
        c = cdll

        c.hvd_abi_version.restype = ctypes.c_int32

        # wire
        c.hvd_crc32.restype = ctypes.c_uint32
        c.hvd_crc32.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        c.hvd_wire_pack_request.restype = ctypes.c_int64
        c.hvd_wire_pack_request.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_int32, ctypes.c_char_p, ctypes.c_int64]
        c.hvd_wire_unpack_request.restype = ctypes.c_int64
        c.hvd_wire_unpack_request.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32)]

        # table
        c.hvd_table_create.restype = ctypes.c_void_p
        c.hvd_table_destroy.argtypes = [ctypes.c_void_p]
        c.hvd_table_begin.restype = ctypes.c_int64
        c.hvd_table_begin.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        c.hvd_table_finish.restype = ctypes.c_int32
        c.hvd_table_finish.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        c.hvd_table_known.restype = ctypes.c_int32
        c.hvd_table_known.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        c.hvd_table_pending.restype = ctypes.c_int64
        c.hvd_table_pending.argtypes = [ctypes.c_void_p]

        # cache
        c.hvd_cache_create.restype = ctypes.c_void_p
        c.hvd_cache_create.argtypes = [ctypes.c_int64]
        c.hvd_cache_destroy.argtypes = [ctypes.c_void_p]
        c.hvd_cache_lookup.restype = ctypes.c_int32
        c.hvd_cache_lookup.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        c.hvd_cache_put.restype = ctypes.c_int32
        c.hvd_cache_put.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64)]
        c.hvd_cache_erase.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        c.hvd_cache_size.restype = ctypes.c_int64
        c.hvd_cache_size.argtypes = [ctypes.c_void_p]
        c.hvd_cache_clear.argtypes = [ctypes.c_void_p]

        # fusion
        c.hvd_plan_buckets.restype = ctypes.c_int64
        c.hvd_plan_buckets.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32)]

        # stall
        c.hvd_stall_create.restype = ctypes.c_void_p
        c.hvd_stall_destroy.argtypes = [ctypes.c_void_p]
        c.hvd_stall_submit.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        c.hvd_stall_done.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        c.hvd_stall_pending.restype = ctypes.c_int64
        c.hvd_stall_pending.argtypes = [ctypes.c_void_p]
        c.hvd_stall_check.restype = ctypes.c_int64
        c.hvd_stall_check.argtypes = [
            ctypes.c_void_p, ctypes.c_double, ctypes.c_double,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_char_p, ctypes.c_int64]

        # timeline
        c.hvd_tl_create.restype = ctypes.c_void_p
        c.hvd_tl_create.argtypes = [ctypes.c_char_p]
        c.hvd_tl_tid.restype = ctypes.c_int32
        c.hvd_tl_tid.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        c.hvd_tl_emit.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_int32, ctypes.c_char_p]
        c.hvd_tl_close.argtypes = [ctypes.c_void_p]

        # metrics
        c.hvd_mtr_create.restype = ctypes.c_void_p
        c.hvd_mtr_destroy.argtypes = [ctypes.c_void_p]
        c.hvd_mtr_add.argtypes = [ctypes.c_void_p, ctypes.c_double]
        c.hvd_mtr_set.argtypes = [ctypes.c_void_p, ctypes.c_double]
        c.hvd_mtr_get.restype = ctypes.c_double
        c.hvd_mtr_get.argtypes = [ctypes.c_void_p]
        c.hvd_hist_create.restype = ctypes.c_void_p
        c.hvd_hist_create.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int32]
        c.hvd_hist_destroy.argtypes = [ctypes.c_void_p]
        c.hvd_hist_observe.argtypes = [ctypes.c_void_p, ctypes.c_double]
        c.hvd_hist_read.restype = ctypes.c_int32
        c.hvd_hist_read.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_uint64)]

        # bayesian optimization
        c.hvd_bo_create.restype = ctypes.c_void_p
        c.hvd_bo_create.argtypes = [
            ctypes.c_int32, ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double), ctypes.c_uint64]
        c.hvd_bo_destroy.argtypes = [ctypes.c_void_p]
        c.hvd_bo_observe.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_double), ctypes.c_double]
        c.hvd_bo_num_obs.restype = ctypes.c_int64
        c.hvd_bo_num_obs.argtypes = [ctypes.c_void_p]
        c.hvd_bo_suggest.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.POINTER(ctypes.c_double)]


def get() -> Optional[_Bindings]:
    """The loaded native bindings, building the library on first call.
    Returns None when native is disabled or unbuildable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("HVD_TPU_NATIVE", "1") in ("0", "false", "FALSE"):
            return None
        try:
            from . import build
            cdll = ctypes.CDLL(build.build())
            b = _Bindings(cdll)
            if b.cdll.hvd_abi_version() != 1:
                cdll = ctypes.CDLL(build.build(force=True))
                b = _Bindings(cdll)
            _lib = b
        except Exception as e:  # toolchain missing, build error, bad .so
            import logging
            logging.getLogger("horovod_tpu").info(
                "native runtime unavailable (%s); using pure-Python "
                "fallbacks", e)
            _lib = None
        return _lib


def available() -> bool:
    return get() is not None
