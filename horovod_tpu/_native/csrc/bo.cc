// Bayesian optimization with Gaussian-process regression.
//
// Native analogue of the reference autotuner's optimizer (/root/reference/
// horovod/common/optim/{bayesian_optimization,gaussian_process}.{h,cc}:
// expected-improvement BO over an RBF-kernel GP, used by ParameterManager to
// tune fusion threshold / cycle time by throughput score). Self-contained
// dense linear algebra (Cholesky) — no Eigen/LBFGS; EI is maximized by
// deterministic pseudo-random candidate search, which at the 2-3 dimensions
// of the tuning space matches gradient ascent in practice and keeps every
// process's suggestion identical for a given observation history (the
// reference achieves cross-rank agreement by having rank 0 tune and
// broadcast; determinism gives us the same property without a broadcast).
#include <cmath>
#include <cstdint>
#include <vector>

#include "common.hpp"

namespace {

struct BO {
  int32_t dim;
  std::vector<double> lo, hi;
  std::vector<std::vector<double>> xs;  // normalized [0,1]^dim
  std::vector<double> ys;               // raw scores (higher = better)
  uint64_t seed;
  double length_scale = 0.2;
  double noise = 1e-6;
};

// xorshift64* — deterministic across platforms.
double next_unit(uint64_t* s) {
  uint64_t x = *s;
  x ^= x >> 12;
  x ^= x << 25;
  x ^= x >> 27;
  *s = x;
  return (double)((x * 0x2545F4914F6CDD1DULL) >> 11) / 9007199254740992.0;
}

double kernel(const std::vector<double>& a, const std::vector<double>& b,
              double ls) {
  double d2 = 0;
  for (size_t i = 0; i < a.size(); i++) {
    double d = a[i] - b[i];
    d2 += d * d;
  }
  return std::exp(-d2 / (2.0 * ls * ls));
}

// Cholesky factorization of A (n x n, row-major) in place: A = L L^T.
// Returns false if not positive definite.
bool cholesky(std::vector<double>& a, int n) {
  for (int i = 0; i < n; i++) {
    for (int j = 0; j <= i; j++) {
      double sum = a[i * n + j];
      for (int k = 0; k < j; k++) sum -= a[i * n + k] * a[j * n + k];
      if (i == j) {
        if (sum <= 0) return false;
        a[i * n + i] = std::sqrt(sum);
      } else {
        a[i * n + j] = sum / a[j * n + j];
      }
    }
    for (int j = i + 1; j < n; j++) a[i * n + j] = 0;
  }
  return true;
}

// Solves L y = b then L^T x = y (in place on b).
void chol_solve(const std::vector<double>& l, int n, std::vector<double>& b) {
  for (int i = 0; i < n; i++) {
    double sum = b[i];
    for (int k = 0; k < i; k++) sum -= l[i * n + k] * b[k];
    b[i] = sum / l[i * n + i];
  }
  for (int i = n - 1; i >= 0; i--) {
    double sum = b[i];
    for (int k = i + 1; k < n; k++) sum -= l[k * n + i] * b[k];
    b[i] = sum / l[i * n + i];
  }
}

double norm_cdf(double z) { return 0.5 * std::erfc(-z / std::sqrt(2.0)); }

double norm_pdf(double z) {
  return std::exp(-0.5 * z * z) / std::sqrt(2.0 * M_PI);
}

}  // namespace

HVD_EXPORT void* hvd_bo_create(int32_t dim, const double* lo,
                               const double* hi, uint64_t seed) {
  auto* b = new BO();
  b->dim = dim;
  b->lo.assign(lo, lo + dim);
  b->hi.assign(hi, hi + dim);
  b->seed = seed ? seed : 0x9E3779B97F4A7C15ULL;
  return b;
}

HVD_EXPORT void hvd_bo_destroy(void* p) { delete static_cast<BO*>(p); }

HVD_EXPORT void hvd_bo_observe(void* p, const double* x, double y) {
  auto* b = static_cast<BO*>(p);
  std::vector<double> xn(b->dim);
  for (int i = 0; i < b->dim; i++) {
    double span = b->hi[i] - b->lo[i];
    xn[i] = span > 0 ? (x[i] - b->lo[i]) / span : 0.0;
  }
  b->xs.push_back(std::move(xn));
  b->ys.push_back(y);
}

HVD_EXPORT int64_t hvd_bo_num_obs(void* p) {
  return (int64_t)static_cast<BO*>(p)->ys.size();
}

// Writes the next point to evaluate into x_out (denormalized). With fewer
// than 2 observations, space-filling pseudo-random exploration; afterwards,
// argmax of expected improvement over `n_cand` candidates. Deterministic for
// a given observation history.
HVD_EXPORT void hvd_bo_suggest(void* p, int32_t n_cand, double* x_out) {
  auto* b = static_cast<BO*>(p);
  int n = (int)b->ys.size();
  uint64_t rng = b->seed + (uint64_t)n * 0xD1B54A32D192ED03ULL;
  if (n_cand <= 0) n_cand = 512;

  auto denorm = [&](const std::vector<double>& xn) {
    for (int i = 0; i < b->dim; i++)
      x_out[i] = b->lo[i] + xn[i] * (b->hi[i] - b->lo[i]);
  };

  if (n < 2) {
    std::vector<double> xn(b->dim);
    for (int i = 0; i < b->dim; i++) xn[i] = next_unit(&rng);
    denorm(xn);
    return;
  }

  // Normalize y for a zero-mean unit-ish-scale GP.
  double mean = 0, var = 0;
  for (double y : b->ys) mean += y;
  mean /= n;
  for (double y : b->ys) var += (y - mean) * (y - mean);
  double sd = std::sqrt(var / n);
  if (sd < 1e-12) sd = 1.0;
  std::vector<double> yn(n);
  double best_y = -1e300;
  for (int i = 0; i < n; i++) {
    yn[i] = (b->ys[i] - mean) / sd;
    if (yn[i] > best_y) best_y = yn[i];
  }

  // K + noise I, Cholesky, alpha = K^-1 y.
  std::vector<double> K((size_t)n * n);
  for (int i = 0; i < n; i++)
    for (int j = 0; j < n; j++) {
      K[(size_t)i * n + j] = kernel(b->xs[i], b->xs[j], b->length_scale);
      if (i == j) K[(size_t)i * n + j] += b->noise;
    }
  if (!cholesky(K, n)) {
    // Degenerate (duplicate points): fall back to exploration.
    std::vector<double> xn(b->dim);
    for (int i = 0; i < b->dim; i++) xn[i] = next_unit(&rng);
    denorm(xn);
    return;
  }
  std::vector<double> alpha = yn;
  chol_solve(K, n, alpha);

  double best_ei = -1;
  std::vector<double> best_x(b->dim, 0.5);
  std::vector<double> kstar(n), v(n);
  for (int c = 0; c < n_cand; c++) {
    std::vector<double> xn(b->dim);
    for (int i = 0; i < b->dim; i++) xn[i] = next_unit(&rng);
    for (int i = 0; i < n; i++)
      kstar[i] = kernel(xn, b->xs[i], b->length_scale);
    // mu = k*^T alpha
    double mu = 0;
    for (int i = 0; i < n; i++) mu += kstar[i] * alpha[i];
    // sigma^2 = k(x,x) - k*^T K^-1 k*  via v = L^-1 k*
    v = kstar;
    for (int i = 0; i < n; i++) {
      double sum = v[i];
      for (int k = 0; k < i; k++) sum -= K[(size_t)i * n + k] * v[k];
      v[i] = sum / K[(size_t)i * n + i];
    }
    double s2 = 1.0 + b->noise;
    for (int i = 0; i < n; i++) s2 -= v[i] * v[i];
    double sigma = s2 > 1e-12 ? std::sqrt(s2) : 0.0;
    double ei;
    const double xi = 0.01;  // exploration margin
    if (sigma <= 0) {
      ei = 0;
    } else {
      double z = (mu - best_y - xi) / sigma;
      ei = (mu - best_y - xi) * norm_cdf(z) + sigma * norm_pdf(z);
    }
    if (ei > best_ei) {
      best_ei = ei;
      best_x = xn;
    }
  }
  denorm(best_x);
}
