// Library identity.
#include "common.hpp"

// Bumped whenever the C API changes shape; the Python loader refuses a
// stale cached .so whose ABI does not match (and rebuilds from source).
HVD_EXPORT int32_t hvd_abi_version() { return 1; }
