// Metric cells: lock-free scalar samples and fixed-bucket histograms.
//
// The metrics registry (metrics.py) instruments the eager dispatch path,
// so a cell update must cost one atomic op — no mutex, no allocation.
// Scalars are atomic doubles (CAS add since fetch_add on floating
// atomics is C++20); histograms keep one atomic counter per bucket plus
// a CAS-accumulated sum. Reads are relaxed snapshots: a scrape races
// concurrent updates by design (Prometheus semantics — monotonic
// counters make torn cross-series reads harmless).
#include "common.hpp"

#include <algorithm>
#include <atomic>

namespace {

struct Cell {
  std::atomic<double> v{0.0};
};

void atomic_add(std::atomic<double>& a, double d) {
  double cur = a.load(std::memory_order_relaxed);
  while (!a.compare_exchange_weak(cur, cur + d,
                                  std::memory_order_relaxed)) {
  }
}

struct Hist {
  int32_t n = 0;                      // finite bucket bounds
  double* bounds = nullptr;           // sorted upper bounds, size n
  std::atomic<uint64_t>* counts = nullptr;  // n + 1 (last = +Inf)
  std::atomic<double> sum{0.0};
  std::atomic<uint64_t> total{0};
  ~Hist() {
    delete[] bounds;
    delete[] counts;
  }
};

}  // namespace

HVD_EXPORT void* hvd_mtr_create() { return new Cell(); }

HVD_EXPORT void hvd_mtr_destroy(void* h) { delete static_cast<Cell*>(h); }

HVD_EXPORT void hvd_mtr_add(void* h, double d) {
  atomic_add(static_cast<Cell*>(h)->v, d);
}

HVD_EXPORT void hvd_mtr_set(void* h, double d) {
  static_cast<Cell*>(h)->v.store(d, std::memory_order_relaxed);
}

HVD_EXPORT double hvd_mtr_get(void* h) {
  return static_cast<Cell*>(h)->v.load(std::memory_order_relaxed);
}

HVD_EXPORT void* hvd_hist_create(const double* bounds, int32_t n) {
  if (n <= 0) return nullptr;
  Hist* h = new Hist();
  h->n = n;
  h->bounds = new double[n];
  std::copy(bounds, bounds + n, h->bounds);
  h->counts = new std::atomic<uint64_t>[n + 1];
  for (int32_t i = 0; i <= n; ++i)
    h->counts[i].store(0, std::memory_order_relaxed);
  return h;
}

HVD_EXPORT void hvd_hist_destroy(void* p) { delete static_cast<Hist*>(p); }

HVD_EXPORT void hvd_hist_observe(void* p, double v) {
  Hist* h = static_cast<Hist*>(p);
  // first bucket whose bound >= v (lower_bound: le semantics), else +Inf
  int32_t idx = static_cast<int32_t>(
      std::lower_bound(h->bounds, h->bounds + h->n, v) - h->bounds);
  h->counts[idx].fetch_add(1, std::memory_order_relaxed);
  atomic_add(h->sum, v);
  h->total.fetch_add(1, std::memory_order_relaxed);
}

HVD_EXPORT int32_t hvd_hist_read(void* p, uint64_t* out_counts,
                                 double* out_sum, uint64_t* out_total) {
  Hist* h = static_cast<Hist*>(p);
  for (int32_t i = 0; i <= h->n; ++i)
    out_counts[i] = h->counts[i].load(std::memory_order_relaxed);
  *out_sum = h->sum.load(std::memory_order_relaxed);
  *out_total = h->total.load(std::memory_order_relaxed);
  return h->n + 1;
}
