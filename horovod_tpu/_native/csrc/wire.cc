// Wire format: compact binary encoding of a collective submission's metadata.
//
// Role of the reference's FlatBuffers Request/Response wire format
// (/root/reference/horovod/common/wire/message.fbs, common/message.{h,cc}):
// the bytes that cross the host control plane and the bytes whose CRC is the
// cross-process consistency fingerprint (controller.cc:378-611 validation is
// replaced on TPU by comparing fingerprints of these messages). Layout is
// fixed little-endian so the pure-Python packer (tensor_table.py) produces
// byte-identical output:
//
//   u8  version (=1)
//   i32 rank
//   u8  kind_len,  kind bytes
//   u16 name_len,  name bytes
//   u8  dtype_len, dtype bytes
//   u8  ndim,      i64 dims[ndim]
//   u16 extra_len, extra bytes
#include "common.hpp"

namespace hvdtpu {

namespace {

const uint32_t* crc_table() {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  return table;
}

struct Writer {
  uint8_t* out;
  int64_t cap;
  int64_t pos = 0;
  bool ok = true;

  void bytes(const void* p, int64_t n) {
    if (pos + n > cap) { ok = false; return; }
    std::memcpy(out + pos, p, n);
    pos += n;
  }
  void u8(uint8_t v) { bytes(&v, 1); }
  void u16(uint16_t v) { uint8_t b[2] = {(uint8_t)(v & 0xff), (uint8_t)(v >> 8)}; bytes(b, 2); }
  void i32(int32_t v) {
    uint8_t b[4];
    for (int i = 0; i < 4; i++) b[i] = (uint8_t)((uint32_t)v >> (8 * i));
    bytes(b, 4);
  }
  void i64(int64_t v) {
    uint8_t b[8];
    for (int i = 0; i < 8; i++) b[i] = (uint8_t)((uint64_t)v >> (8 * i));
    bytes(b, 8);
  }
};

struct Reader {
  const uint8_t* in;
  int64_t len;
  int64_t pos = 0;
  bool ok = true;

  bool need(int64_t n) {
    if (pos + n > len) { ok = false; return false; }
    return true;
  }
  uint8_t u8() { if (!need(1)) return 0; return in[pos++]; }
  uint16_t u16() {
    if (!need(2)) return 0;
    uint16_t v = (uint16_t)(in[pos] | (in[pos + 1] << 8));
    pos += 2;
    return v;
  }
  int32_t i32() {
    if (!need(4)) return 0;
    uint32_t v = 0;
    for (int i = 0; i < 4; i++) v |= (uint32_t)in[pos + i] << (8 * i);
    pos += 4;
    return (int32_t)v;
  }
  int64_t i64() {
    if (!need(8)) return 0;
    uint64_t v = 0;
    for (int i = 0; i < 8; i++) v |= (uint64_t)in[pos + i] << (8 * i);
    pos += 8;
    return (int64_t)v;
  }
  // copies up to cap-1 bytes + NUL into dst
  bool str(int64_t n, char* dst, int64_t cap) {
    if (!need(n)) return false;
    int64_t c = n < cap - 1 ? n : cap - 1;
    if (dst && cap > 0) {
      std::memcpy(dst, in + pos, c);
      dst[c] = '\0';
    }
    pos += n;
    return true;
  }
};

}  // namespace

uint32_t crc32_ieee(const uint8_t* data, int64_t len) {
  const uint32_t* t = crc_table();
  uint32_t c = 0xFFFFFFFFu;
  for (int64_t i = 0; i < len; i++) c = t[(c ^ data[i]) & 0xff] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

}  // namespace hvdtpu

HVD_EXPORT uint32_t hvd_crc32(const uint8_t* buf, int64_t len) {
  return hvdtpu::crc32_ieee(buf, len);
}

HVD_EXPORT int64_t hvd_wire_pack_request(
    const char* name, const int64_t* shape, int32_t ndim, const char* dtype,
    const char* kind, const char* extra, int32_t rank, uint8_t* out,
    int64_t cap) {
  using namespace hvdtpu;
  int64_t name_len = (int64_t)std::strlen(name);
  int64_t dtype_len = (int64_t)std::strlen(dtype);
  int64_t kind_len = (int64_t)std::strlen(kind);
  int64_t extra_len = extra ? (int64_t)std::strlen(extra) : 0;
  if (name_len > 0xFFFF || dtype_len > 0xFF || kind_len > 0xFF ||
      extra_len > 0xFFFF || ndim > 0xFF || ndim < 0)
    return -1;
  Writer w{out, cap};
  w.u8(1);
  w.i32(rank);
  w.u8((uint8_t)kind_len);
  w.bytes(kind, kind_len);
  w.u16((uint16_t)name_len);
  w.bytes(name, name_len);
  w.u8((uint8_t)dtype_len);
  w.bytes(dtype, dtype_len);
  w.u8((uint8_t)ndim);
  for (int32_t i = 0; i < ndim; i++) w.i64(shape[i]);
  w.u16((uint16_t)extra_len);
  if (extra_len) w.bytes(extra, extra_len);
  return w.ok ? w.pos : -1;
}

HVD_EXPORT int64_t hvd_wire_unpack_request(
    const uint8_t* buf, int64_t len, char* name_out, int64_t name_cap,
    int64_t* shape_out, int32_t* ndim_io, char* dtype_out, int64_t dtype_cap,
    char* kind_out, int64_t kind_cap, char* extra_out, int64_t extra_cap,
    int32_t* rank_out) {
  using namespace hvdtpu;
  Reader r{buf, len};
  if (r.u8() != 1) return -1;
  int32_t rank = r.i32();
  int64_t kind_len = r.u8();
  if (!r.str(kind_len, kind_out, kind_cap)) return -1;
  int64_t name_len = r.u16();
  if (!r.str(name_len, name_out, name_cap)) return -1;
  int64_t dtype_len = r.u8();
  if (!r.str(dtype_len, dtype_out, dtype_cap)) return -1;
  int32_t ndim = r.u8();
  if (ndim > *ndim_io) return -1;
  for (int32_t i = 0; i < ndim; i++) {
    int64_t d = r.i64();
    if (shape_out) shape_out[i] = d;
  }
  *ndim_io = ndim;
  int64_t extra_len = r.u16();
  if (!r.str(extra_len, extra_out, extra_cap)) return -1;
  if (!r.ok) return -1;
  if (rank_out) *rank_out = rank;
  return r.pos;
}
