// Response cache: LRU of validated submission fingerprints.
//
// Native analogue of the reference ResponseCache (/root/reference/horovod/
// common/response_cache.{h,cc}): the reference caches negotiated Responses
// keyed by name+shape+dtype so steady-state cycles skip the rank-0
// round-trip. On TPU the negotiation being skipped is the cross-process
// metadata consistency exchange (collectives._check_consistency): a hit means
// this exact (name, shape, dtype, op) was already validated across processes,
// so the device round-trip is skipped. Eviction must be reported to the
// caller so every process invalidates the same entries (the reference syncs
// cache bits across ranks; here identical deterministic LRU state on every
// process plays that role).
#include <cstdint>
#include <list>
#include <mutex>
#include <unordered_map>

#include "common.hpp"

namespace {

struct Cache {
  std::mutex mu;
  int64_t capacity;
  std::list<uint64_t> lru;  // front = most recent
  std::unordered_map<uint64_t, std::list<uint64_t>::iterator> pos;
};

}  // namespace

HVD_EXPORT void* hvd_cache_create(int64_t capacity) {
  auto* c = new Cache();
  c->capacity = capacity;
  return c;
}

HVD_EXPORT void hvd_cache_destroy(void* p) { delete static_cast<Cache*>(p); }

// 1 = hit (entry refreshed to MRU), 0 = miss.
HVD_EXPORT int32_t hvd_cache_lookup(void* p, uint64_t key) {
  auto* c = static_cast<Cache*>(p);
  std::lock_guard<std::mutex> lk(c->mu);
  auto it = c->pos.find(key);
  if (it == c->pos.end()) return 0;
  c->lru.splice(c->lru.begin(), c->lru, it->second);
  return 1;
}

// Inserts `key` as MRU. Returns the evicted key via *evicted and 1 if an
// eviction happened, else 0.
HVD_EXPORT int32_t hvd_cache_put(void* p, uint64_t key, uint64_t* evicted) {
  auto* c = static_cast<Cache*>(p);
  std::lock_guard<std::mutex> lk(c->mu);
  if (c->capacity <= 0) return 0;
  auto it = c->pos.find(key);
  if (it != c->pos.end()) {
    c->lru.splice(c->lru.begin(), c->lru, it->second);
    return 0;
  }
  int32_t evict = 0;
  if ((int64_t)c->lru.size() >= c->capacity) {
    uint64_t victim = c->lru.back();
    c->lru.pop_back();
    c->pos.erase(victim);
    if (evicted) *evicted = victim;
    evict = 1;
  }
  c->lru.push_front(key);
  c->pos.emplace(key, c->lru.begin());
  return evict;
}

HVD_EXPORT void hvd_cache_erase(void* p, uint64_t key) {
  auto* c = static_cast<Cache*>(p);
  std::lock_guard<std::mutex> lk(c->mu);
  auto it = c->pos.find(key);
  if (it == c->pos.end()) return;
  c->lru.erase(it->second);
  c->pos.erase(it);
}

HVD_EXPORT int64_t hvd_cache_size(void* p) {
  auto* c = static_cast<Cache*>(p);
  std::lock_guard<std::mutex> lk(c->mu);
  return (int64_t)c->pos.size();
}

HVD_EXPORT void hvd_cache_clear(void* p) {
  auto* c = static_cast<Cache*>(p);
  std::lock_guard<std::mutex> lk(c->mu);
  c->lru.clear();
  c->pos.clear();
}
