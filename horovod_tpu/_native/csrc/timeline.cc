// Chrome-tracing timeline writer with a dedicated writer thread.
//
// Native analogue of the reference TimelineWriter (/root/reference/horovod/
// common/timeline.{h,cc}: record queue drained by a writer thread,
// timeline.h:47-75). Submitting threads pay a mutex push of a pre-sized
// record; JSON formatting and file I/O happen on the writer thread.
// Events stream to disk continuously so a killed job still leaves a loadable
// trace (chrome tracing tolerates a missing closing bracket). The per-tensor
// state machine stays in Python (timeline.py); this layer owns tids,
// timestamps (steady_clock relative to creation) and the file.
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>

#include "common.hpp"

namespace {

using Clock = std::chrono::steady_clock;

struct Record {
  std::string name;
  char ph;            // B, E, i, M
  int32_t tid;
  double ts_us;
  std::string args_json;  // pre-rendered JSON object ("" = none)
  bool meta_thread_name;  // M record: args = {"name": name}
};

struct Timeline {
  std::FILE* f = nullptr;
  Clock::time_point t0;
  std::mutex mu;
  std::condition_variable cv;
  std::deque<Record> q;
  bool closing = false;
  std::thread writer;
  std::mutex tid_mu;
  std::unordered_map<std::string, int32_t> tids;
  int32_t next_tid = 1;

  double now_us() {
    return std::chrono::duration<double, std::micro>(Clock::now() - t0)
        .count();
  }

  void push(Record&& r) {
    {
      std::lock_guard<std::mutex> lk(mu);
      if (closing) return;
      q.push_back(std::move(r));
    }
    cv.notify_one();
  }

  static void json_escape(const std::string& in, std::string* out) {
    for (char c : in) {
      switch (c) {
        case '"': *out += "\\\""; break;
        case '\\': *out += "\\\\"; break;
        case '\n': *out += "\\n"; break;
        case '\t': *out += "\\t"; break;
        default:
          if ((unsigned char)c < 0x20) {
            char buf[8];
            std::snprintf(buf, sizeof buf, "\\u%04x", c);
            *out += buf;
          } else {
            *out += c;
          }
      }
    }
  }

  void write_record(const Record& r) {
    std::string name;
    json_escape(r.name, &name);
    char head[160];
    if (r.ph == 'M') {
      std::fprintf(f,
                   "{\"name\": \"thread_name\", \"ph\": \"M\", \"pid\": 0, "
                   "\"tid\": %d, \"args\": {\"name\": \"%s\"}},\n",
                   r.tid, name.c_str());
      return;
    }
    std::snprintf(head, sizeof head,
                  "{\"name\": \"%s\", \"ph\": \"%c\", \"pid\": 0, "
                  "\"tid\": %d, \"ts\": %.3f",
                  name.c_str(), r.ph, r.tid, r.ts_us);
    std::fputs(head, f);
    if (r.ph == 'i') std::fputs(", \"s\": \"g\"", f);
    if (!r.args_json.empty()) {
      std::fputs(", \"args\": ", f);
      std::fputs(r.args_json.c_str(), f);
    }
    std::fputs("},\n", f);
  }

  void run() {
    std::fputs("[\n", f);
    int64_t n = 0;
    std::unique_lock<std::mutex> lk(mu);
    for (;;) {
      cv.wait(lk, [&] { return !q.empty() || closing; });
      while (!q.empty()) {
        Record r = std::move(q.front());
        q.pop_front();
        lk.unlock();
        write_record(r);
        if (++n % 64 == 0) std::fflush(f);
        lk.lock();
      }
      if (closing) break;
      lk.unlock();
      std::fflush(f);
      lk.lock();
    }
    lk.unlock();
    std::fputs("{}]\n", f);
    std::fclose(f);
  }
};

}  // namespace

HVD_EXPORT void* hvd_tl_create(const char* path) {
  auto* tl = new Timeline();
  tl->f = std::fopen(path, "w");
  if (!tl->f) {
    delete tl;
    return nullptr;
  }
  tl->t0 = Clock::now();
  tl->writer = std::thread([tl] { tl->run(); });
  return tl;
}

// Registers `tensor` on first use (emitting the thread_name metadata record)
// and returns its tid.
HVD_EXPORT int32_t hvd_tl_tid(void* p, const char* tensor) {
  auto* tl = static_cast<Timeline*>(p);
  int32_t tid;
  bool fresh = false;
  {
    std::lock_guard<std::mutex> lk(tl->tid_mu);
    auto it = tl->tids.find(tensor);
    if (it != tl->tids.end()) {
      tid = it->second;
    } else {
      tid = tl->next_tid++;
      tl->tids.emplace(tensor, tid);
      fresh = true;
    }
  }
  if (fresh) tl->push(Record{tensor, 'M', tid, 0.0, "", true});
  return tid;
}

// ph: "B" begin, "E" end, "i" instant. args_json may be NULL.
HVD_EXPORT void hvd_tl_emit(void* p, const char* name, const char* ph,
                            int32_t tid, const char* args_json) {
  auto* tl = static_cast<Timeline*>(p);
  tl->push(Record{name ? name : "", ph[0], tid, tl->now_us(),
                  args_json ? args_json : "", false});
}

HVD_EXPORT void hvd_tl_close(void* p) {
  auto* tl = static_cast<Timeline*>(p);
  {
    std::lock_guard<std::mutex> lk(tl->mu);
    tl->closing = true;
  }
  tl->cv.notify_one();
  tl->writer.join();
  delete tl;
}
