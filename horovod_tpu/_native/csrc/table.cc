// Submission table: duplicate-name detection + handle allocation.
//
// Native analogue of the reference TensorQueue (/root/reference/horovod/
// common/tensor_queue.{h,cc}: AddToTensorQueue rejects in-flight duplicate
// names with DUPLICATE_NAME_ERROR) fused with the Torch HandleManager
// (/root/reference/horovod/torch/handle_manager.{h,cc}: integer handles for
// async ops). Results stay on the Python side (they are jax Arrays); the
// native table owns the mutexed name->handle bookkeeping that sits on every
// eager submission.
#include <mutex>
#include <string>
#include <unordered_map>

#include "common.hpp"

namespace {

struct Table {
  std::mutex mu;
  std::unordered_map<std::string, int64_t> in_flight;
  std::unordered_map<int64_t, std::string> handles;
  int64_t next_handle = 0;
};

}  // namespace

HVD_EXPORT void* hvd_table_create() { return new Table(); }

HVD_EXPORT void hvd_table_destroy(void* t) { delete static_cast<Table*>(t); }

// Returns a fresh handle id, or -1 if `name` is already in flight.
HVD_EXPORT int64_t hvd_table_begin(void* t, const char* name) {
  auto* tab = static_cast<Table*>(t);
  std::lock_guard<std::mutex> lk(tab->mu);
  std::string n(name);
  if (tab->in_flight.count(n)) return -1;
  int64_t h = tab->next_handle++;
  tab->in_flight.emplace(n, h);
  tab->handles.emplace(h, std::move(n));
  return h;
}

// Returns 1 if the handle was known and removed, 0 otherwise.
HVD_EXPORT int32_t hvd_table_finish(void* t, int64_t h) {
  auto* tab = static_cast<Table*>(t);
  std::lock_guard<std::mutex> lk(tab->mu);
  auto it = tab->handles.find(h);
  if (it == tab->handles.end()) return 0;
  tab->in_flight.erase(it->second);
  tab->handles.erase(it);
  return 1;
}

HVD_EXPORT int32_t hvd_table_known(void* t, int64_t h) {
  auto* tab = static_cast<Table*>(t);
  std::lock_guard<std::mutex> lk(tab->mu);
  return tab->handles.count(h) ? 1 : 0;
}

HVD_EXPORT int64_t hvd_table_pending(void* t) {
  auto* tab = static_cast<Table*>(t);
  std::lock_guard<std::mutex> lk(tab->mu);
  return (int64_t)tab->in_flight.size();
}
