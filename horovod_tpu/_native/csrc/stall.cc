// Stall inspector bookkeeping.
//
// Native analogue of the reference StallInspector (/root/reference/horovod/
// common/stall_inspector.{h,cc}): tracks when each named submission first
// appeared and reports the ones that have waited past the warn/shutdown
// deadlines. The clock lives here (steady_clock at submit) so the Python
// polling thread only pays one ctypes call per poll; logging/raising stays in
// Python (stall.py) where the message can name ranks and knobs.
#include <chrono>
#include <cstdio>
#include <mutex>
#include <string>
#include <unordered_map>

#include "common.hpp"

namespace {

using Clock = std::chrono::steady_clock;

struct Entry {
  Clock::time_point t0;
  bool warned = false;
};

struct Stall {
  std::mutex mu;
  std::unordered_map<std::string, Entry> pending;
};

}  // namespace

HVD_EXPORT void* hvd_stall_create() { return new Stall(); }

HVD_EXPORT void hvd_stall_destroy(void* p) { delete static_cast<Stall*>(p); }

HVD_EXPORT void hvd_stall_submit(void* p, const char* name) {
  auto* s = static_cast<Stall*>(p);
  std::lock_guard<std::mutex> lk(s->mu);
  s->pending.emplace(std::string(name), Entry{Clock::now(), false});
}

HVD_EXPORT void hvd_stall_done(void* p, const char* name) {
  auto* s = static_cast<Stall*>(p);
  std::lock_guard<std::mutex> lk(s->mu);
  s->pending.erase(std::string(name));
}

HVD_EXPORT int64_t hvd_stall_pending(void* p) {
  auto* s = static_cast<Stall*>(p);
  std::lock_guard<std::mutex> lk(s->mu);
  return (int64_t)s->pending.size();
}

// Scans the table: entries pending longer than warn_s that have not been
// reported yet are marked warned and their names written newline-joined into
// `out` (truncated at cap). Returns the number of newly-warned entries.
// *shutdown_hit is set to 1 when shutdown_s > 0 and any entry exceeds it.
HVD_EXPORT int64_t hvd_stall_check(void* p, double warn_s, double shutdown_s,
                                   int32_t* shutdown_hit, char* out,
                                   int64_t cap) {
  auto* s = static_cast<Stall*>(p);
  auto now = Clock::now();
  int64_t n_new = 0;
  int64_t pos = 0;
  if (cap > 0) out[0] = '\0';
  std::lock_guard<std::mutex> lk(s->mu);
  for (auto& kv : s->pending) {
    double waited =
        std::chrono::duration<double>(now - kv.second.t0).count();
    if (shutdown_s > 0 && waited > shutdown_s && shutdown_hit)
      *shutdown_hit = 1;
    if (waited > warn_s && !kv.second.warned) {
      int64_t len = (int64_t)kv.first.size();
      if (pos + len + 2 >= cap) continue;  // report on a later scan
      kv.second.warned = true;
      n_new++;
      if (pos > 0) out[pos++] = '\n';
      std::memcpy(out + pos, kv.first.data(), len);
      pos += len;
      out[pos] = '\0';
    }
  }
  return n_new;
}
