// Common declarations for the horovod_tpu native host runtime.
//
// TPU-native analogue of the reference's C++ core (/root/reference/horovod/
// common/): on TPU the data plane is XLA-compiled collectives, so what stays
// native is the *host* runtime around it — submission table, response cache,
// fusion planning, stall detection, timeline writing, autotuning — the same
// components the reference implements in horovod/common/{tensor_queue,
// response_cache,fusion_buffer_manager,stall_inspector,timeline,
// parameter_manager}.{h,cc}, re-designed for a single-controller-per-host
// world and exposed through a flat C API consumed over ctypes.
#pragma once

#include <cstdint>
#include <cstring>

#if defined(_WIN32)
#define HVD_EXPORT extern "C" __declspec(dllexport)
#else
#define HVD_EXPORT extern "C" __attribute__((visibility("default")))
#endif

namespace hvdtpu {

// IEEE CRC-32 (matches Python zlib.crc32 so fingerprints agree between the
// native and pure-Python wire paths).
uint32_t crc32_ieee(const uint8_t* data, int64_t len);

}  // namespace hvdtpu
