// Fusion planner: greedy in-order gradient bucketing.
//
// Native analogue of the reference's response fusion (/root/reference/
// horovod/common/controller.cc:640-761 FuseResponses + fusion_buffer_manager):
// consecutive tensors share a bucket until the byte threshold is exceeded.
// On TPU a bucket is one jit dispatch, not one flat staging buffer, so dtype
// mixing within a bucket is allowed (XLA handles the per-dtype fusion).
// Semantics are kept identical to the pure-Python fallback
// (horovod_tpu/fusion.py plan_buckets) — tests assert parity.
#include "common.hpp"

// Writes the bucket index of each tensor into out[i]; returns the number of
// buckets. threshold <= 0 disables fusion (one bucket per tensor).
HVD_EXPORT int64_t hvd_plan_buckets(const int64_t* nbytes, int64_t n,
                                    int64_t threshold, int32_t* out) {
  if (n <= 0) return 0;
  if (threshold <= 0) {
    for (int64_t i = 0; i < n; i++) out[i] = (int32_t)i;
    return n;
  }
  int64_t bucket = 0;
  int64_t cur_bytes = 0;
  bool cur_nonempty = false;
  for (int64_t i = 0; i < n; i++) {
    if (cur_nonempty && cur_bytes + nbytes[i] > threshold) {
      bucket++;
      cur_bytes = 0;
      cur_nonempty = false;
    }
    out[i] = (int32_t)bucket;
    cur_bytes += nbytes[i];
    cur_nonempty = true;
  }
  return bucket + 1;
}
