"""Response cache: skip re-validating steady-state submissions.

TPU-native analogue of the reference ResponseCache
(/root/reference/horovod/common/response_cache.{h,cc}): the reference caches
negotiated Responses keyed by name+shape+dtype so steady-state training
cycles replace the full rank-0 negotiation with two bitwise allreduces
(CacheCoordinator::sync, response_cache.h:104-160). On TPU the expensive part
being skipped is the cross-process metadata consistency exchange
(collectives._check_consistency's device round-trip): a hit means this exact
(name, shape, dtype, op) fingerprint was already validated identically on
every process, so the exchange is skipped.

Coherence argument (replaces the reference's cache-bit sync): every process
runs the same deterministic LRU with the same capacity and sees the same
sequence of validated submissions — a submission is only inserted *after* a
successful cross-process validation proved all processes submitted it in the
same step — so cache state never diverges across processes on the hit path.
A miss on any process is at worst a redundant re-validation, never a skipped
one, because a process only skips when *its own* cache proves prior
validation. Capacity comes from ``HVD_TPU_CACHE_CAPACITY`` (alias
``HOROVOD_CACHE_CAPACITY``, reference default 1024; 0 disables caching).

Backed by the native LRU (csrc/cache.cc) when built, with an OrderedDict
fallback.
"""

import collections
import threading
from typing import Optional

from . import metrics as _metrics
from ._native import get as _native_get

# Cache efficiency is the steady-state health signal of the collective
# path: a hit means the consistency exchange (a device round-trip) was
# skipped; a miss storm on one rank shows up in metrics_allgather_summary
# long before it shows up as throughput loss.
_M_HITS = _metrics.counter(
    "hvd_tpu_response_cache_hits_total",
    "Response-cache hits (consistency exchange skipped).")
_M_MISSES = _metrics.counter(
    "hvd_tpu_response_cache_misses_total",
    "Response-cache misses (full cross-process exchange performed).")
_M_EVICTIONS = _metrics.counter(
    "hvd_tpu_response_cache_evictions_total",
    "Response-cache LRU evictions (capacity pressure; evicted "
    "fingerprints re-validate on next submission).")


class ResponseCache:
    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self._nat = _native_get()
        self._h = None
        if self._nat is not None:
            self._h = self._nat.cdll.hvd_cache_create(self.capacity)
        self._lock = threading.Lock()
        self._lru: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()

    def __del__(self):
        if getattr(self, "_h", None) and self._nat:
            try:
                self._nat.cdll.hvd_cache_destroy(self._h)
            except Exception:
                pass

    def lookup(self, key: int) -> bool:
        """True when `key` was previously validated (refreshes LRU order)."""
        if self.capacity <= 0:
            _M_MISSES.inc()  # disabled cache: every check re-exchanges
            return False
        if self._h is not None:
            hit = bool(self._nat.cdll.hvd_cache_lookup(self._h, key))
            (_M_HITS if hit else _M_MISSES).inc()
            return hit
        with self._lock:
            if key in self._lru:
                self._lru.move_to_end(key)
                hit = True
            else:
                hit = False
        (_M_HITS if hit else _M_MISSES).inc()
        return hit

    def put(self, key: int) -> Optional[int]:
        """Insert a validated key; returns the evicted key, if any."""
        if self.capacity <= 0:
            return None
        if self._h is not None:
            import ctypes
            evicted = ctypes.c_uint64(0)
            if self._nat.cdll.hvd_cache_put(self._h, key,
                                            ctypes.byref(evicted)):
                _M_EVICTIONS.inc()
                return int(evicted.value)
            return None
        with self._lock:
            if key in self._lru:
                self._lru.move_to_end(key)
                return None
            victim = None
            if len(self._lru) >= self.capacity:
                victim, _ = self._lru.popitem(last=False)
            self._lru[key] = None
        if victim is not None:
            _M_EVICTIONS.inc()
        return victim

    def erase(self, key: int) -> None:
        """Invalidate one entry (reference: stalled tensors are invalidated,
        stall_inspector.cc:31-60)."""
        if self._h is not None:
            self._nat.cdll.hvd_cache_erase(self._h, key)
            return
        with self._lock:
            self._lru.pop(key, None)

    def clear(self) -> None:
        if self._h is not None:
            self._nat.cdll.hvd_cache_clear(self._h)
            return
        with self._lock:
            self._lru.clear()

    def __len__(self) -> int:
        if self._h is not None:
            return int(self._nat.cdll.hvd_cache_size(self._h))
        with self._lock:
            return len(self._lru)
