"""Deterministic fault injection for horovod_tpu.

The elastic recovery machinery (stall shutdown -> blacklist ->
re-rendezvous, reference horovod/common/elastic.py:147-168 +
stall_inspector.cc:31-90) only earns trust if its failure paths can be
exercised on demand, repeatably, without actually pulling network cables.
This module is a process-wide registry of *named injection sites*
(:class:`FaultPoint`) driven by one environment knob:

    HVD_TPU_FAULT_SPEC="rendezvous.get:error:rate=0.3;worker.step:crash:step=12"
    HVD_TPU_FAULT_SEED=7

Grammar — ``;``-separated entries, each ``site:field[:field...]``:

* **site** matches a fault point exactly, or as a dot-boundary prefix
  (``rendezvous`` matches ``rendezvous.get`` and ``rendezvous.put``;
  ``collective`` matches every verb).
* one field names the **kind**:
  - ``error``      raise the site's characteristic exception (a transient
                   socket-shaped error at host-plane I/O sites, an
                   internal error at collective sites);
  - ``neterror``   always raise :class:`InjectedTransientFault`
                   (exercises retry paths regardless of the site default);
  - ``delay=S``    sleep ``S`` seconds (latency / congestion);
  - ``hang[=S]``   sleep ``S`` (default effectively forever) — what the
                   stall inspector exists to catch;
  - ``crash``      ``os._exit`` — a hard worker kill, the elastic
                   driver's recovery scenario.
  - ``bitflip``    XOR one mantissa/exponent bit in one tensor leaf —
                   silent data corruption, delivered through the site's
                   ``corrupt`` handler (data-carrying sites only);
  - ``nan``        overwrite one element of one leaf with NaN — the
                   soft-SDC variant of ``bitflip``, same delivery.
* remaining ``k=v`` fields scope the rule:
  - ``rate=P``     fire with probability P per hit (default 1.0);
  - ``after=N``    ignore the first N hits of the point;
  - ``step=N``     fire exactly on hit N (1-based) — e.g. crash on the
                   12th ``worker.step`` (one hit per ``State.commit()``),
                   or ``worker.mesh:crash:step=N:rank=R`` to hard-kill
                   rank R mid-sharded-step (one hit per
                   ``parallel.train.run_mesh_step``) — the mesh-aware
                   recovery drill (docs/elastic.md);
  - ``times=N`` / ``once``  cap total injections for the rule;
  - ``rank=R``     only inject on the process whose rank is R.

**Determinism.** Every probabilistic decision comes from a
``random.Random`` seeded by ``(HVD_TPU_FAULT_SEED, rule text, site)`` —
string-seeded, so it is stable across processes and runs (Python's
``hash()`` salting never enters). Given the same seed and the same
sequence of hits at a site, the same faults fire. Each
:class:`FaultPoint` owns a private copy of each matching rule's counters
and RNG, so two points matched by one prefix rule cannot perturb each
other's schedules.

**Zero overhead when off.** With no spec configured, ``fire()`` is one
module-global load and one ``is None`` test. Nothing is parsed, no RNG
exists, no lock is taken.

Tests (and only tests) reconfigure in-process via :func:`configure`;
production processes parse the env exactly once, on the first hit of any
fault point, and a re-exec'd elastic worker re-parses naturally in its
fresh interpreter.
"""

import logging
import os
import random
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

from . import config as _config
from . import metrics as _metrics

log = logging.getLogger("horovod_tpu.faults")

_M_INJECTED = _metrics.counter(
    "hvd_tpu_faults_injected_total",
    "Faults injected by the HVD_TPU_FAULT_SPEC harness, by site and kind.",
    labels=("site", "kind"))

#: exit code used by ``crash`` faults — distinct from common exit codes so
#: a chaos harness can tell an injected kill from an organic failure.
CRASH_EXIT_CODE = 29

_KINDS = ("error", "neterror", "delay", "hang", "crash", "preempt",
          "bitflip", "nan")


class InjectedFault(RuntimeError):
    """Generic injected failure (collective/internal sites). RuntimeError,
    so the dispatcher classifies it fatal and surfaces it as
    HorovodInternalError — the elastic retry loop's recovery trigger."""


class InjectedTransientFault(ConnectionError):
    """Injected transient failure (host-plane I/O sites). ConnectionError,
    so :mod:`horovod_tpu.retry` classifies it transient and the hardened
    call sites absorb it."""


class FaultSpecError(ValueError):
    """HVD_TPU_FAULT_SPEC could not be parsed."""


class _Rule:
    """One parsed spec entry (site prefix + kind + scoping params)."""

    __slots__ = ("site", "kind", "seconds", "rate", "after", "step",
                 "times", "rank", "grace", "text", "index")

    def __init__(self, site: str, kind: str, seconds: float, rate: float,
                 after: int, step: Optional[int], times: Optional[int],
                 rank: Optional[int], grace: float, text: str, index: int):
        self.site = site
        self.kind = kind
        self.seconds = seconds
        self.rate = rate
        self.after = after
        self.step = step
        self.times = times
        self.rank = rank
        self.grace = grace
        self.text = text
        self.index = index

    def matches(self, site: str) -> bool:
        return site == self.site or site.startswith(self.site + ".")


class _BoundRule:
    """A rule bound to ONE fault point: private hit/injection counters and
    a private deterministic RNG, so prefix rules matched by several points
    keep independent, reproducible schedules."""

    __slots__ = ("rule", "hits", "injected", "rng")

    def __init__(self, rule: _Rule, seed: int, site: str):
        self.rule = rule
        self.hits = 0
        self.injected = 0
        # string seeding goes through SHA-512 in CPython — stable across
        # processes and runs, unlike object hash()
        self.rng = random.Random(f"{seed}|{rule.index}|{rule.text}|{site}")

    def decide(self) -> bool:
        r = self.rule
        self.hits += 1
        if r.times is not None and self.injected >= r.times:
            return False
        if r.rank is not None and _current_rank() != r.rank:
            return False
        if r.step is not None:
            fire = self.hits == r.step
        else:
            if self.hits <= r.after:
                return False
            fire = r.rate >= 1.0 or self.rng.random() < r.rate
        if fire:
            self.injected += 1
        return fire


def _parse_entry(entry: str, index: int) -> _Rule:
    fields = [f.strip() for f in entry.split(":")]
    if len(fields) < 2 or not fields[0]:
        raise FaultSpecError(
            f"fault spec entry {entry!r}: want site:kind[:param=value...]")
    site = fields[0]
    kind = None
    seconds = 0.0
    rate = 1.0
    after = 0
    grace = 0.0
    step = times = rank = None
    for field in fields[1:]:
        key, eq, value = field.partition("=")
        if not eq:
            if key == "once":
                times = 1
            elif key in ("error", "neterror", "crash", "preempt",
                         "bitflip", "nan"):
                kind = key
            elif key == "hang":
                kind, seconds = "hang", 1e9
            else:
                raise FaultSpecError(
                    f"fault spec entry {entry!r}: unknown field {field!r}")
            continue
        try:
            if key in ("delay", "hang"):
                kind, seconds = key, float(value)
            elif key == "rate":
                rate = float(value)
            elif key == "after":
                after = int(value)
            elif key == "step":
                step = int(value)
            elif key == "times":
                times = int(value)
            elif key == "rank":
                rank = int(value)
            elif key == "grace":
                grace = float(value)
            else:
                raise FaultSpecError(
                    f"fault spec entry {entry!r}: unknown param {key!r}")
        except ValueError as e:
            if isinstance(e, FaultSpecError):
                raise
            raise FaultSpecError(
                f"fault spec entry {entry!r}: bad value for {key!r}") from e
    if kind is None:
        raise FaultSpecError(
            f"fault spec entry {entry!r}: no kind among {_KINDS}")
    return _Rule(site, kind, seconds, rate, after, step, times, rank,
                 grace, entry, index)


def parse_spec(spec: str) -> List[_Rule]:
    return [_parse_entry(e.strip(), i)
            for i, e in enumerate(spec.split(";")) if e.strip()]


class _FaultRegistry:
    #: gen rides ON the registry (not a separate module global) so a
    #: FaultPoint reading one _ACTIVE reference always sees a consistent
    #: (rules, seed, gen) triple — two separate globals could be observed
    #: mid-configure and bind an old spec under a new generation number.
    __slots__ = ("rules", "seed", "gen")

    def __init__(self, rules: Sequence[_Rule], seed: int, gen: int):
        self.rules = tuple(rules)
        self.seed = seed
        self.gen = gen


_lock = threading.Lock()
#: None = injection off. Checked unlocked on the hot path; configure()
#: publishes a fully built registry in one reference assignment.
_ACTIVE: Optional[_FaultRegistry] = None
#: bumped on every configure(); FaultPoints cache bound rules per generation
_GEN = 0
_configured = False


def configure(spec: Optional[str] = None, seed: Optional[int] = None) -> None:
    """(Re)build the process-wide registry. With no arguments, reads
    ``HVD_TPU_FAULT_SPEC`` / ``HVD_TPU_FAULT_SEED`` through the knob
    registry. An empty spec disables injection entirely."""
    global _ACTIVE, _GEN, _configured
    cfg = _config.Config()
    if spec is None:
        spec = cfg.get(_config.FAULT_SPEC)
    if seed is None:
        seed = cfg.get(_config.FAULT_SEED)
    rules = parse_spec(spec or "")
    with _lock:
        _GEN += 1
        _ACTIVE = _FaultRegistry(rules, int(seed), _GEN) if rules else None
        _configured = True
    if rules:
        log.warning("fault injection ACTIVE (%d rule(s), seed=%s): %s",
                    len(rules), seed, spec)


def ensure_configured() -> None:
    """Parse the env spec once — called from ``basics.init()`` so a
    malformed ``HVD_TPU_FAULT_SPEC`` fails fast as a startup
    :class:`FaultSpecError` instead of surfacing at the first fault
    point mid-training (where the elastic loop would classify it
    recoverable and spin restore->fail forever). Deliberately does NOT
    rebuild an already-configured registry: an in-process elastic reset
    (``shutdown(); init()``) must keep the hit counters, or ``once``
    faults would re-fire every generation."""
    if not _configured:
        configure()


def enabled() -> bool:
    ensure_configured()
    return _ACTIVE is not None


def _current_rank() -> int:
    from . import basics
    if basics.is_initialized():
        return basics.world().rank()
    try:
        return int(os.environ.get("HVD_TPU_RANK") or -1)
    except ValueError:
        return -1


class FaultPoint:
    """One named injection site. Construct once (module/instance scope) and
    call :meth:`fire` on the guarded path; :meth:`check` is the no-raise
    variant for owners that map an ``error`` fault onto a domain-specific
    failure (e.g. the stall inspector's deadline flag).

    ``exc``: exception class raised for ``error`` faults at this site —
    the site owner declares what a fault *looks like* there (a rendezvous
    fault is a socket error; a collective fault is an internal error).
    """

    __slots__ = ("site", "_exc", "_bound", "_gen", "_lock")

    def __init__(self, site: str, exc: Callable[[str], BaseException] =
                 InjectedFault):
        self.site = site
        self._exc = exc
        self._bound: Tuple[_BoundRule, ...] = ()
        self._gen = -1
        self._lock = threading.Lock()

    def _resolve(self, reg: _FaultRegistry) -> Tuple[_BoundRule, ...]:
        if self._gen != reg.gen:
            with self._lock:
                if self._gen != reg.gen:
                    self._bound = tuple(
                        _BoundRule(r, reg.seed, self.site)
                        for r in reg.rules if r.matches(self.site))
                    self._gen = reg.gen
        return self._bound

    def fire(self, crash: Optional[Callable[[], None]] = None,
             preempt: Optional[Callable[[float], None]] = None,
             corrupt: Optional[Callable[[str, random.Random], None]] = None
             ) -> None:
        """Inject any matching faults; raises / sleeps / exits per kind.

        ``crash``: optional site-owned substitute for ``os._exit`` on
        ``crash`` faults. A worker-side site has nothing gentler than a
        hard process kill, but a *launcher*-side site (the rendezvous
        server) must simulate its component dying without taking the
        whole job control plane down with it — the owner passes the
        simulation (e.g. ``KVStoreServer._simulate_crash``) here.

        ``preempt``: site-owned delivery of a preemption *notice* on
        ``preempt`` faults — called with the rule's ``grace`` seconds.
        Unlike every other kind this one doesn't fail anything: it
        simulates the fleet scheduler announcing a reclaim, and the
        owner forwards it into the graceful-drain path. A site without
        a handler ignores the rule (notice kinds only mean something
        where a notice channel exists).

        ``corrupt``: site-owned delivery of silent data corruption on
        ``bitflip``/``nan`` faults — called with the kind and the bound
        rule's deterministic RNG so the owner picks the leaf/bit/element
        reproducibly. Like ``preempt`` this doesn't raise: SDC is by
        definition silent, the poisoned value flows onward until a guard
        catches it. A site without a handler ignores the rule (only
        data-carrying sites can be corrupted).
        """
        if _ACTIVE is None and _configured:
            return  # hot path: injection off
        err = self._evaluate(crash=crash, preempt=preempt, corrupt=corrupt)
        if err is not None:
            raise err

    def check(self) -> bool:
        """Like :meth:`fire`, but an ``error``/``neterror`` fault is
        *returned* as True instead of raised — for sites that translate an
        injected fault into their own failure mode."""
        if _ACTIVE is None and _configured:
            return False
        return self._evaluate() is not None

    def _evaluate(self, crash: Optional[Callable[[], None]] = None,
                  preempt: Optional[Callable[[float], None]] = None,
                  corrupt: Optional[Callable[[str, random.Random], None]]
                  = None) -> Optional[BaseException]:
        if not _configured:
            configure()
        reg = _ACTIVE   # one read: rules + seed + gen stay consistent
        if reg is None:
            return None
        err: Optional[BaseException] = None
        for bound in self._resolve(reg):
            with self._lock:
                fire = bound.decide()
            if not fire:
                continue
            rule = bound.rule
            _M_INJECTED.labels(site=self.site, kind=rule.kind).inc()
            log.warning("fault injected: site=%s kind=%s (rule %r, hit %d)",
                        self.site, rule.kind, rule.text, bound.hits)
            if rule.kind in ("delay", "hang"):
                time.sleep(rule.seconds)
            elif rule.kind == "preempt":
                if preempt is not None:
                    preempt(rule.grace)
                else:
                    log.warning(
                        "preempt fault matched site %s but the site has "
                        "no notice handler; ignoring", self.site)
            elif rule.kind in ("bitflip", "nan"):
                if corrupt is not None:
                    corrupt(rule.kind, bound.rng)
                else:
                    log.warning(
                        "%s fault matched site %s but the site has no "
                        "corrupt handler; ignoring", rule.kind, self.site)
            elif rule.kind == "crash":
                if crash is not None:
                    crash()
                    continue
                import sys
                sys.stdout.flush()
                sys.stderr.flush()
                os._exit(CRASH_EXIT_CODE)
            elif rule.kind == "neterror":
                err = InjectedTransientFault(
                    f"injected transient fault at {self.site} "
                    f"(rule {rule.text!r})")
            else:  # error
                err = self._exc(
                    f"injected fault at {self.site} (rule {rule.text!r})")
        return err
