"""Online autotuning of host-plane knobs, scored by throughput.

TPU-native analogue of the reference ParameterManager
(/root/reference/horovod/common/parameter_manager.{h,cc}: warmup/sample
schedule scoring bytes/sec, Bayesian optimization over tunables,
parameter_manager.h:33-105) with its optimizer
(common/optim/{bayesian_optimization,gaussian_process}.{h,cc}). On TPU the
background cycle time and hierarchical on/off knobs don't exist — XLA owns
the schedule — so the tuned surface is the **fusion threshold** (gradient
bucket size): it controls eager-plane dispatch granularity, the
latency/overlap trade the reference tunes its threshold for.

Protocol (reference parameter_manager.cc Update/Tune):

* every eager reduction step reports ``record(bytes, seconds)``;
* after ``HVD_TPU_AUTOTUNE_STEPS_PER_SAMPLE`` steps a sample completes with
  score = bytes/sec; the first ``HVD_TPU_AUTOTUNE_WARMUP_SAMPLES`` samples
  are discarded (compilation noise);
* each scored sample feeds the GP/EI optimizer (native csrc/bo.cc, with a
  deterministic golden-section-style Python fallback), which proposes the
  next threshold;
* after ``HVD_TPU_AUTOTUNE_BAYES_OPT_MAX_SAMPLES`` samples the knob locks
  on the best value seen and tuning moves to the next knob (coordinate
  descent over the 1-D optimizer — the reference tunes its multi-knob set
  jointly, but its cycle-time/hierarchy knobs don't exist here);
* knobs, in order: the fusion threshold (bucket size), then the
  host-packing cutoff (``HVD_TPU_PACK_CUTOFF``, the hybrid fusion
  buffer's pack-vs-solo member boundary). Each phase re-runs warmup
  (changed cutoffs change program structure, so fresh compiles pollute
  the first sample).

Cross-process agreement (reference: rank 0 tunes and broadcasts,
controller.cc:33-47 SynchronizeParameters): local throughput measurements
differ across processes, and divergent thresholds would make processes build
different bucket structures — i.e. different collective sequences. So in a
multi-process world rank 0's proposal is broadcast at every sample boundary;
boundaries align because every process counts the same ``record()`` calls.
"""

import ctypes
import math
import time
from typing import Optional

from . import config as _config
from . import metrics as _metrics
from ._native import get as _native_get

# The live tuned values as gauges: when throughput shifts after a knob
# lock, the operator sees WHICH threshold the tuner settled on without
# grepping the autotune log.
_M_FUSION_GAUGE = _metrics.gauge(
    "hvd_tpu_autotune_fusion_threshold_bytes",
    "Current gradient-bucket fusion threshold (autotuned or configured).")
_M_CUTOFF_GAUGE = _metrics.gauge(
    "hvd_tpu_autotune_pack_cutoff_bytes",
    "Current host-packing cutoff (autotuned or configured).")
_M_SAMPLES = _metrics.counter(
    "hvd_tpu_autotune_samples_total",
    "Autotune throughput samples scored (warmup samples excluded).")

# Tuned knobs in phase order: (config name, log2 lo, log2 hi).
# Fusion threshold searches [1 MB, 256 MB]; pack cutoff [4 KB, 4 MB].
_KNOBS = (
    ("FUSION_THRESHOLD", 20.0, 28.0),
    ("PACK_CUTOFF", 12.0, 22.0),
)
# kept for existing callers/tests of the fallback optimizer
_LOG2_LO, _LOG2_HI = _KNOBS[0][1], _KNOBS[0][2]


class _PythonFallbackOptimizer:
    """Deterministic 1-D maximizer used when the native GP/BO is unbuilt:
    sweeps a coarse grid, then golden-section refines around the incumbent.
    Same interface as the native BO (observe/suggest), same determinism
    property (identical history -> identical suggestion)."""

    def __init__(self, lo: float, hi: float):
        self._lo, self._hi = lo, hi
        # 5-point grid over THIS knob's bounds (a class-level grid baked
        # to the fusion-threshold range sent the PACK_CUTOFF phase
        # probing 64-256 MB cutoffs — round-5 review finding)
        self._GRID = [lo + i * (hi - lo) / 4.0 for i in range(5)]
        self._obs = []

    def observe(self, x: float, y: float):
        self._obs.append((x, y))

    def suggest(self) -> float:
        n = len(self._obs)
        if n < len(self._GRID):
            return self._GRID[n]
        best_x, _ = max(self._obs, key=lambda o: o[1])
        # shrinking probes alternating around the incumbent
        k = n - len(self._GRID)
        step = (self._hi - self._lo) / (2.0 ** (k // 2 + 2))
        probe = best_x + (step if k % 2 == 0 else -step)
        return min(self._hi, max(self._lo, probe))


class _NativeOptimizer:
    def __init__(self, nat, lo: float, hi: float, seed: int = 1234):
        self._nat = nat
        self._b = nat.cdll.hvd_bo_create(
            1, (ctypes.c_double * 1)(lo), (ctypes.c_double * 1)(hi), seed)

    def __del__(self):
        if getattr(self, "_b", None):
            try:
                self._nat.cdll.hvd_bo_destroy(self._b)
            except Exception:
                pass

    def observe(self, x: float, y: float):
        self._nat.cdll.hvd_bo_observe(self._b, (ctypes.c_double * 1)(x), y)

    def suggest(self) -> float:
        out = (ctypes.c_double * 1)()
        self._nat.cdll.hvd_bo_suggest(self._b, 512, out)
        return float(out[0])


class ParameterManager:
    """Created by ``init()`` when HVD_TPU_AUTOTUNE is set; consulted by the
    eager reduction path (optimizer.py) each step."""

    def __init__(self, world):
        cfg = world.config
        self._world = world
        self._warmup_samples = int(
            cfg.get(_config.AUTOTUNE_WARMUP_SAMPLES))
        self._warmup_left = self._warmup_samples
        self._steps_per_sample = max(
            1, int(cfg.get(_config.AUTOTUNE_STEPS_PER_SAMPLE)))
        self._max_samples = int(
            cfg.get(_config.AUTOTUNE_BAYES_OPT_MAX_SAMPLES))
        self._log_path = cfg.get(_config.AUTOTUNE_LOG)
        self._nat = _native_get()
        self._values = {name: int(cfg.get(getattr(_config, name)))
                        for name, _lo, _hi in _KNOBS}
        self._phase = 0
        self._samples_done = 0
        self._step_in_sample = 0
        self._bytes_acc = 0
        self._time_acc = 0.0
        self._finished = False
        self._publish_gauges()
        self._enter_phase(0)

    def _publish_gauges(self) -> None:
        _M_FUSION_GAUGE.set(self._values["FUSION_THRESHOLD"])
        _M_CUTOFF_GAUGE.set(self._values["PACK_CUTOFF"])

    def _enter_phase(self, phase: int) -> None:
        self._phase = phase
        name, lo, hi = _KNOBS[phase]
        if self._nat is not None:
            self._opt = _NativeOptimizer(self._nat, lo, hi)
        else:
            self._opt = _PythonFallbackOptimizer(lo, hi)
        self._best = (self._values[name], -1.0)
        self._samples_done = 0
        self._warmup_left = self._warmup_samples

    @property
    def _knob_name(self) -> str:
        return _KNOBS[self._phase][0]

    # -- interface consulted by the reduction path ---------------------------
    @property
    def active(self) -> bool:
        return not self._finished

    @property
    def fusion_threshold(self) -> int:
        return self._values["FUSION_THRESHOLD"]

    def record(self, nbytes: int, seconds: float) -> None:
        """Report one eager reduction step's traffic and wall time."""
        if self._finished:
            return
        self._bytes_acc += int(nbytes)
        self._time_acc += float(seconds)
        self._step_in_sample += 1
        if self._step_in_sample < self._steps_per_sample:
            return
        score = self._bytes_acc / max(self._time_acc, 1e-9)
        self._step_in_sample = 0
        self._bytes_acc = 0
        self._time_acc = 0.0
        if self._warmup_left > 0:
            self._warmup_left -= 1
            self._log(f"warmup {self._knob_name}="
                      f"{self._values[self._knob_name]} "
                      f"score={score:.3e} (discarded)")
            return
        self._observe_and_advance(score)

    def _observe_and_advance(self, score: float) -> None:
        name = self._knob_name
        value = self._values[name]
        x = math.log2(max(value, 1))
        if score > self._best[1]:
            self._best = (value, score)
        self._samples_done += 1
        _M_SAMPLES.inc()
        self._log(f"sample {self._samples_done} {name}={value} "
                  f"score={score:.3e} bytes/sec")
        if self._samples_done >= self._max_samples:
            # per-process best scores differ; rank 0's pick is adopted
            # everywhere, like every other proposal
            self._values[name] = int(self._sync(float(self._best[0])))
            self._world.config.set(name, self._values[name])
            self._publish_gauges()
            if self._phase + 1 < len(_KNOBS):
                self._log(f"knob locked: {name}={self._values[name]} "
                          f"score={self._best[1]:.3e}; tuning "
                          f"{_KNOBS[self._phase + 1][0]} next")
                self._enter_phase(self._phase + 1)
            else:
                self._finished = True
                summary = " ".join(
                    f"{n}={self._values[n]}" for n, _l, _h in _KNOBS)
                self._log(f"tuning complete: {summary} "
                          f"score={self._best[1]:.3e}")
            return
        self._opt.observe(x, score)
        proposal = 1 << int(round(self._sync(self._opt.suggest())))
        self._values[name] = proposal
        self._world.config.set(name, self._values[name])
        self._publish_gauges()

    def _sync(self, proposal: float) -> float:
        """Adopt rank 0's proposal in a multi-process world (reference:
        SynchronizeParameters broadcast, controller.cc:33-47)."""
        if self._world.num_processes <= 1:
            return proposal
        import numpy as np
        from . import collectives as _c
        out = _c.broadcast(np.array([proposal], np.float64), root_rank=0,
                           name="hvd_tpu.autotune.param")
        return float(np.asarray(out)[0])

    def _log(self, msg: str) -> None:
        if not self._log_path or self._world.process_id != 0:
            return
        try:
            with open(self._log_path, "a") as f:
                f.write(f"{time.strftime('%Y-%m-%d %H:%M:%S')} {msg}\n")
        except OSError:
            pass


def maybe_create(world) -> Optional[ParameterManager]:
    if not world.config.get(_config.AUTOTUNE):
        return None
    return ParameterManager(world)
