"""High-level Estimator: fit / evaluate / predict for flax models.

Reference surface: the Spark ML estimators
(/root/reference/horovod/spark/keras/estimator.py:105-379 KerasEstimator,
spark/torch/estimator.py:84-304 TorchEstimator — wrap a model + optimizer +
loss, fit on prepared data across workers, return a servable transformer).
TPU-native redesign: no Spark dependency — the estimator owns the training
loop over the eager data-parallel plane (DistributedOptimizer bucketed
allreduce), uses :mod:`horovod_tpu.data` for sharding/prefetch,
:mod:`horovod_tpu.callbacks` for broadcast/metric-averaging/LR hooks, and
:mod:`horovod_tpu.checkpoint` for persistence. ``fit`` returns a
:class:`History`; the fitted estimator predicts locally.
"""

import logging
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

log = logging.getLogger("horovod_tpu.estimator")


@dataclass
class History:
    """Per-epoch metric logs (shape of keras History.history)."""
    history: Dict[str, List[float]] = field(default_factory=dict)

    def append(self, logs: Dict[str, float]):
        for k, v in logs.items():
            self.history.setdefault(k, []).append(v)


class _SdcSentry:
    """Per-``fit()`` silent-data-corruption defense wiring, built only
    when ``HVD_TPU_SDC_GUARD`` is on (docs/robustness.md, SDC section):
    every step runs through the guard, parameters are fingerprinted
    every ``HVD_TPU_SDC_FINGERPRINT_EVERY`` applied steps, and the
    policy escalates detections to skip / rollback / quarantine."""

    def __init__(self, manager):
        from . import sdc as _sdc
        self.sdc = _sdc
        self.guard = _sdc.StepGuard()
        self.monitor = _sdc.FingerprintMonitor()
        self.policy = _sdc.SdcPolicy()
        self.manager = manager      # CheckpointManager (rollback target)
        self.step = 0               # applied (non-skipped) steps
        self.dropped = 0
        self.rollbacks = 0

    def safe_loss(self, loss) -> float:
        # a poisoned step must not leak NaN into the epoch logs (the
        # metric-average callback allreduces them): report the EWMA,
        # i.e. the recent clean loss level
        lv = float(loss)
        if np.isfinite(lv):
            return lv
        ewma = self.guard._ewma
        return float(ewma) if ewma is not None else 0.0


class Estimator:
    """Train a flax module data-parallel with the reference's 5-line recipe
    folded in (LR scaling, optimizer wrapping, initial broadcast, metric
    averaging).

    Args:
      model: flax module with ``init``/``apply``.
      optimizer: optax transformation (unscaled base LR; world scaling is
        applied like the reference examples do).
      loss_fn: ``(logits_or_outputs, targets) -> scalar`` (defaults to
        softmax cross-entropy with integer labels).
      metrics: dict name -> ``(outputs, targets) -> scalar``.
    """

    def __init__(self, model, optimizer=None,
                 loss_fn: Optional[Callable] = None,
                 metrics: Optional[Dict[str, Callable]] = None,
                 scale_lr_by_world: bool = True,
                 checkpoint_dir: Optional[str] = None,
                 seed: int = 0):
        self.model = model
        self._base_optimizer = optimizer
        self.loss_fn = loss_fn
        self.metrics = metrics or {}
        self.scale_lr = scale_lr_by_world
        self.checkpoint_dir = checkpoint_dir
        self.seed = seed
        self.params = None
        self._opt = None
        self._opt_state = None
        self._predict_cache = None

    # -- internals -----------------------------------------------------------
    def _default_loss(self):
        import optax

        def loss(outputs, targets):
            return optax.softmax_cross_entropy_with_integer_labels(
                outputs, targets).mean()
        return loss

    def _build(self, x0):
        import jax
        import optax
        import horovod_tpu as hvd
        if self._base_optimizer is None:
            lr = 1e-3 * (hvd.dp_size() if self.scale_lr else 1)
            self._base_optimizer = optax.adam(lr)
        self._opt = hvd.DistributedOptimizer(self._base_optimizer)
        if self.params is None:
            self.params = self.model.init(
                jax.random.PRNGKey(self.seed), x0)
        self._opt_state = self._opt.init(self.params)
        loss_fn = self.loss_fn or self._default_loss()
        model = self.model

        @jax.jit
        def loss_and_grads(params, x, y):
            def f(p):
                return loss_fn(model.apply(p, x), y)
            return jax.value_and_grad(f)(params)

        self._loss_and_grads = loss_and_grads

    # -- public API ----------------------------------------------------------
    def fit(self, x, y, epochs: int = 1, batch_size: int = 32,
            callbacks: Optional[Sequence] = None,
            validation_data=None, shard: bool = True,
            verbose: bool = False) -> History:
        import optax
        import horovod_tpu as hvd
        from . import callbacks as cbs
        from . import data as hdata

        if shard:
            x, y = hdata.shard_dataset((np.asarray(x), np.asarray(y)))
        if self._opt is None:
            self._build(x[:1])

        steps = len(x) // batch_size
        if steps == 0:
            raise ValueError(
                f"per-process shard has {len(x)} samples, fewer than "
                f"batch_size={batch_size}: no full batch to train on. "
                f"Reduce batch_size or provide more data per process "
                f"(static SPMD shapes require full batches).")
        run = cbs.TrainingRun(params=self.params, steps_per_epoch=steps)
        cb_list = [cbs.BroadcastGlobalVariablesCallback(0),
                   cbs.MetricAverageCallback()]
        cb_list += list(callbacks or [])
        if self.checkpoint_dir:
            from .checkpoint import CheckpointCallback
            cb_list.append(CheckpointCallback(self.checkpoint_dir))
        cl = cbs.CallbackList(cb_list, run)

        sentry = None
        from . import config as _config
        if _config.live_config().get(_config.SDC_GUARD):
            manager = None
            for cb in cb_list:
                if hasattr(cb, "manager"):       # CheckpointCallback
                    manager = cb.manager
                    break
            sentry = _SdcSentry(manager)

        history = History()
        cl.on_train_begin()
        for epoch in range(epochs):
            cl.on_epoch_begin(epoch)
            logs: Dict[str, float] = {}
            feed = hdata.prefetch_to_device(
                hdata.batches((x, y), batch_size, seed=self.seed + epoch))
            try:
                for batch, (bx, by) in enumerate(feed):
                    cl.on_batch_begin(batch)
                    if sentry is None:
                        loss, grads = self._loss_and_grads(
                            run.params, bx, by)
                        updates, self._opt_state = self._opt.update(
                            grads, self._opt_state, run.params)
                        run.params = optax.apply_updates(run.params,
                                                         updates)
                        logs = {"loss": float(loss)}
                    else:
                        logs = self._guarded_step(run, bx, by, sentry,
                                                  optax)
                    cl.on_batch_end(batch, logs)
            finally:
                feed.close()
            for mname, mfn in self.metrics.items():
                logs[mname] = float(mfn(
                    self.model.apply(run.params, x), y))
            if validation_data is not None:
                vx, vy = validation_data
                logs["val_loss"] = float(self._eval_loss(run.params, vx, vy))
            cl.on_epoch_end(epoch, logs)
            if sentry is not None and "checkpoint_step" in logs:
                # the save is only a rollback *candidate*: it becomes
                # last-good after HVD_TPU_SDC_CONFIRM_STEPS clean steps
                sentry.policy.on_saved(logs["checkpoint_step"])
            history.append(logs)
            if verbose and hvd.rank() == 0:
                print(f"epoch {epoch}: " + " ".join(
                    f"{k}={v:.4f}" for k, v in logs.items()))
        cl.on_train_end(logs if epochs > 0 else None)  # drains async saves
        self.params = run.params
        return history

    # -- SDC defense (docs/robustness.md, SDC section) -----------------------
    def _guarded_step(self, run, bx, by, sentry, optax) -> Dict[str, float]:
        """One training step under the SDC guard. A tripped guard skips
        the poisoned update and retries the batch ONCE (a transient
        one-shot corruption — the drill, a cosmic-ray flip — recomputes
        clean, keeping the run bit-identical to an uncorrupted one);
        a second trip drops the batch. Fingerprint divergence or a
        repeat pattern escalates to a rollback to last-good."""
        sdc = sentry.sdc
        loss = float("nan")
        for attempt in (0, 1):
            loss, grads = self._loss_and_grads(run.params, bx, by)
            grads = sdc.corrupt_grads(grads)     # worker.grads drill site
            det = sentry.guard.check(grads, loss)
            if det is None:
                updates, self._opt_state = self._opt.update(
                    grads, self._opt_state, run.params)
                run.params = optax.apply_updates(run.params, updates)
                sentry.step += 1
                promoted = sentry.policy.on_clean_step()
                if promoted is not None and sentry.manager is not None:
                    sentry.manager.promote_last_good(promoted)
                fdet = sentry.monitor.maybe_check(sentry.step, run.params)
                if fdet is not None and \
                        sentry.policy.on_detection(fdet) == sdc.ROLLBACK:
                    self._sdc_rollback(run, sentry)
                return {"loss": sentry.safe_loss(loss)}
            if sentry.policy.on_detection(det) == sdc.ROLLBACK:
                self._sdc_rollback(run, sentry)
                return {"loss": sentry.safe_loss(loss)}
        sentry.dropped += 1
        log.warning("sdc: batch dropped — the guard tripped on the "
                    "retry too (persistent corruption on this input)")
        return {"loss": sentry.safe_loss(loss)}

    def _sdc_rollback(self, run, sentry) -> None:
        """Restore params from the last-good checkpoint and reset the
        optimizer state (it postdates the restored params). Without a
        promoted last-good target the poisoned update is skipped — a
        rollback onto unconfirmed state would just reload the suspect
        parameters it is meant to purge."""
        mgr = sentry.manager
        if mgr is None or mgr.last_good_step is None:
            log.warning("sdc: rollback requested but no last-good "
                        "checkpoint promoted yet; skipping the poisoned "
                        "update instead")
            return
        mgr.wait_until_finished()
        run.params = mgr.restore_last_good(target=run.params)
        self._opt_state = self._opt.init(run.params)
        self.params = run.params
        sentry.policy.on_rollback()
        sentry.rollbacks += 1
        log.warning("sdc: rolled back to last-good step %d",
                    mgr.last_good_step)

    def _eval_loss(self, params, x, y):
        loss_fn = self.loss_fn or self._default_loss()
        return loss_fn(self.model.apply(params, np.asarray(x)),
                       np.asarray(y))

    def evaluate(self, x, y) -> Dict[str, float]:
        """Loss + metrics on (x, y), averaged across processes."""
        import horovod_tpu as hvd
        if self.params is None:
            raise RuntimeError("call fit() before evaluate()")
        out: Dict[str, float] = {
            "loss": float(self._eval_loss(self.params, x, y))}
        preds = self.model.apply(self.params, np.asarray(x))
        for mname, mfn in self.metrics.items():
            out[mname] = float(mfn(preds, np.asarray(y)))
        if hvd.is_initialized() and hvd.size() > 1:
            for k in sorted(out):
                out[k] = float(np.asarray(hvd.allreduce(
                    np.float64(out[k]), name=f"estimator.eval.{k}")))
        return out

    def predict(self, x):
        """Forward pass on ``x`` (leading dim = batch), returned unpadded.

        Inputs are zero-padded to power-of-two buckets and run through a
        per-bucket jit cache (:class:`horovod_tpu.serving.batcher.
        BucketedForward`, the serving batcher's engine), so repeated
        predicts of varying sizes hit a handful of compiled programs
        instead of recompiling per distinct length. The returned rows are
        exactly the old eager ``model.apply`` values (padding rows are
        computed and discarded; the model must be row-wise, which every
        batched-inference model is)."""
        if self.params is None:
            raise RuntimeError("call fit() before predict()")
        x = np.asarray(x)
        if x.ndim < 2:
            # a single unbatched sample: no leading batch dim to bucket
            # (padding it would slice the wrong axis) — apply directly,
            # the historical behavior
            return self.model.apply(self.params, x)
        if self._predict_cache is None:
            from .serving.batcher import BucketedForward
            self._predict_cache = BucketedForward(self.model.apply)
        return self._predict_cache.apply_padded(self.params, x)

    # -- persistence (reference: estimator Store / model transformer) --------
    def save(self, directory: str, step: int = 0):
        from . import checkpoint as ckpt
        return ckpt.save(directory, step, self.params, force=True)

    def load(self, directory: str, step: Optional[int] = None):
        from . import checkpoint as ckpt
        self.params = ckpt.restore(directory, step=step)
        return self
