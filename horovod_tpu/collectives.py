"""Eager (host-plane) collectives for horovod_tpu.

The reference's data plane enqueues tensors to a background C++ thread that
negotiates readiness and calls NCCL/MPI/Gloo
(/root/reference/horovod/common/operations.cc:815-966 Enqueue*,
ops/nccl_operations.cc:125-175). On TPU the data plane is XLA: an eager
collective is a tiny jitted SPMD program over the ``'proc'`` axis of the
:class:`~horovod_tpu.mesh.WorldMesh` — each process contributes its local
value as one shard of a global array, XLA lowers the reduction to ICI/DCN
collectives, and the replicated result is read back locally. JAX's async
dispatch replaces the reference's handle/finalizer-thread pipelining
(gpu_operations.cc:60-87): ``*_async`` returns immediately with a handle and
``synchronize`` blocks on the device future.

Semantics parity with the reference API
(horovod/torch/mpi_ops.py, horovod/tensorflow/mpi_ops.py):

* ``allreduce(tensor, average/op, prescale_factor, postscale_factor, name)``
* ``allgather(tensor, name)`` — concat along dim 0, ragged first dims allowed
  (collective_operations.cc:87-194 allgatherv displacement math)
* ``broadcast(tensor, root_rank, name)``
* ``alltoall(tensor, splits, name)``
* ``grouped_allreduce([tensors], ...)`` — one fused dispatch
* duplicate in-flight names raise (tensor_queue.cc DUPLICATE_NAME_ERROR)
* mismatched shape/dtype/op across processes raise instead of deadlock
  (controller.cc:378-611 validation; default-on, disable with
  ``HVD_TPU_CHECK_CONSISTENCY=0``)

Ops beyond a single process require ``init()`` with a multi-process world;
with one process they are exact local equivalents (size-1 semantics, as the
reference's tests use when run without a launcher).
"""

import enum
import queue
import threading
import time as _time
from typing import List, Optional, Sequence

import numpy as np

from . import _schedule as _sched
from . import basics as _basics
from . import config as _config
from . import faults as _faults
from . import metrics as _metrics
from . import retry as _retry
from . import timeline as _tl
from . import tracing as _tracing
from .exceptions import HorovodInternalError, TensorValidationError
from .tensor_table import Handle, TensorTable, metadata_fingerprint

# -- telemetry: the always-on counterpart of the timeline (metrics.py).
# Children are pre-bound per verb at import so the submit/dispatch hot
# path pays plain increments, no label lookups; eager registration also
# makes every series visible in scrapes before the first collective.
_M_OPS = _metrics.counter(
    "hvd_tpu_collective_ops_total",
    "Eager collectives submitted, by verb.", labels=("op",))
_M_BYTES = _metrics.counter(
    "hvd_tpu_collective_bytes_total",
    "Payload bytes submitted to eager collectives, by verb.",
    labels=("op",))
_M_LATENCY = _metrics.histogram(
    "hvd_tpu_collective_dispatch_seconds",
    "Dispatcher-thread stage+dispatch wall time per eager collective, by "
    "verb (consistency exchange, staging, XLA dispatch; device "
    "completion is asynchronous).", labels=("op",))
_OP_METRICS = {
    kind: (_M_OPS.labels(op=kind), _M_BYTES.labels(op=kind),
           _M_LATENCY.labels(op=kind))
    for kind in ("allreduce", "grouped_allreduce", "allgather",
                 "broadcast", "grouped_broadcast", "alltoall")}
_M_QUEUE_DEPTH = _metrics.gauge(
    "hvd_tpu_dispatcher_queue_depth",
    "Eager collectives currently queued on the dispatcher thread.")
_M_CONSISTENCY = _metrics.counter(
    "hvd_tpu_consistency_checks_total",
    "Cross-process metadata consistency checks, by result "
    "(cached = ResponseCache fast path skipped the exchange).",
    labels=("result",))
_M_CONSISTENCY_CACHED = _M_CONSISTENCY.labels(result="cached")
_M_CONSISTENCY_EXCHANGED = _M_CONSISTENCY.labels(result="exchanged")
_M_CONSISTENCY_FAILED = _M_CONSISTENCY.labels(result="failed")
# Trace-time lowerings (the in-jit fast path). Incremented when a verb
# called with JAX tracers lowers straight to an XLA collective instead
# of submitting to the dispatcher — so this counts COMPILATIONS (once
# per trace), not steps: a steady training loop shows it flat while
# hvd_tpu_collective_ops_total stays flat too, which together is the
# "zero dispatcher hops" evidence the tests assert.
_M_INJIT = _metrics.counter(
    "hvd_tpu_injit_lowerings_total",
    "Collective verbs lowered in-trace to XLA collectives (counted per "
    "compilation, not per step), by verb.", labels=("op",))
_INJIT_METRICS = {
    kind: _M_INJIT.labels(op=kind)
    for kind in ("allreduce", "grouped_allreduce", "allgather",
                 "broadcast", "grouped_broadcast", "alltoall")}


# Chaos sites on the dispatch path (faults.py): one point per verb, fired
# at the TOP of the dispatched closure — before the consistency exchange
# or any SPMD dispatch, so an injected fault (or its retry) can never
# leave this rank's exchange sequence mispaired with its peers'. With no
# HVD_TPU_FAULT_SPEC these are single-branch no-ops.
_FAULT_POINTS = {
    kind: _faults.FaultPoint(f"collective.{kind}")
    for kind in ("allreduce", "grouped_allreduce", "allgather",
                 "broadcast", "grouped_broadcast", "alltoall")}


def _observed(kind: str, nbytes: int, fn):
    """Count a submission now (caller thread: submissions are recorded
    even if the dispatcher never runs them) and wrap ``fn`` so its
    dispatcher-thread wall time lands in the per-verb latency histogram."""
    ops_c, bytes_c, lat_h = _OP_METRICS[kind]
    ops_c.inc()
    bytes_c.inc(nbytes)
    fp = _FAULT_POINTS[kind]

    def wrapped():
        t0 = _time.perf_counter()
        try:
            fp.fire()
            return fn()
        finally:
            lat_h.observe(_time.perf_counter() - t0)
    return wrapped


class ReduceOp(enum.Enum):
    """Reduction ops (reference: Average/Sum/Adasum in
    horovod/torch/mpi_ops.py:40-44; Min/Max/Product added for completeness)."""
    AVERAGE = "average"
    SUM = "sum"
    ADASUM = "adasum"
    MIN = "min"
    MAX = "max"
    PRODUCT = "product"


Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT

_name_lock = threading.Lock()
_name_counter = 0


def _auto_name(kind: str) -> str:
    global _name_counter
    with _name_lock:
        _name_counter += 1
        return f"{kind}.noname.{_name_counter}"


def _world():
    return _basics.world()


def _table(w) -> TensorTable:
    if getattr(w, "_tensor_table", None) is None:
        w._tensor_table = TensorTable(w)
    return w._tensor_table


def _jnp():
    import jax.numpy as jnp
    return jnp


def _jax():
    import jax
    return jax


# ---------------------------------------------------------------------------
# Jitted SPMD programs over the world mesh, cached per (world, signature).
# This cache is the TPU-shaped descendant of the reference ResponseCache
# (response_cache.{h,cc}): steady-state calls skip all planning.
# ---------------------------------------------------------------------------

def _jit_cache(w) -> dict:
    if getattr(w, "_collective_jit_cache", None) is None:
        import collections
        w._collective_jit_cache = collections.OrderedDict()
    return w._collective_jit_cache


def _get_program(w, key, builder):
    """Compiled-program cache with an LRU bound.

    Most keys derive from shapes/dtypes and stabilize quickly, but some
    carry per-call data (ragged alltoallv's padded max), so a long run
    with data-dependent patterns would otherwise grow the cache — and
    the XLA executables it pins — without bound.
    ``HVD_TPU_PROGRAM_CACHE_CAPACITY`` caps it (its own knob: the
    response cache's CACHE_CAPACITY tunes a fingerprint table whose
    ideal size is unrelated, and an eviction here costs a recompile). A
    floor of 16 keeps tiny configurations from thrashing the handful of
    programs every step uses; eviction order is LRU, identical on every
    rank because the SPMD lockstep makes key streams identical.
    """
    cache = _jit_cache(w)
    fn = cache.get(key)
    if fn is None:
        fn = builder()
        cache[key] = fn
        cap = w.config.get(_config.PROGRAM_CACHE_CAPACITY)
        if cap and len(cache) > max(int(cap), 16):
            cache.popitem(last=False)
    else:
        cache.move_to_end(key)
    return fn


_DTYPE_STR: dict = {}


def _dtype_str(dt) -> str:
    """Interned str(dtype). numpy's ``dtype.__str__`` costs ~7us a call
    (it re-derives the name each time); the eager dispatch path asks for
    it up to 2x per group member, which the round-5 profile showed as the
    single largest Python cost of a grouped dispatch. np.dtype objects
    hash in nanoseconds, so intern the mapping once."""
    s = _DTYPE_STR.get(dt)
    if s is None:
        s = _DTYPE_STR[dt] = str(dt)
    return s


def _zeros_like_staged(v):
    """Zero contribution that PRESERVES staging residency. The grouped
    dispatch routes members by host/device residency (hybrid fusion
    buffer), so a joined rank substituting host zeros for device-resident
    gradients would compile a different SPMD program than its active
    peers — a deadlock, not an error. Device members get device zeros."""
    jax = _jax()
    if isinstance(v, jax.Array):
        return _jnp().zeros(v.shape, v.dtype)
    return np.zeros(v.shape, v.dtype)


def _stage_input(t):
    """Coerce a collective input for staging WITHOUT forcing device data
    through the host: a fully-addressable jax array is used as-is
    (``device_put`` in ``_global_from_local`` moves it device-to-device if
    needed), everything else becomes numpy. ``np.asarray`` on a jax array
    would read it back to the host only to ship it straight back — the
    round-4 microbenchmark exists to catch exactly this class of staging
    waste (reference analogue: the CudaOnCPU staging fallback vs the
    direct-GPU path, torch/mpi_ops_v2.cc:92)."""
    jax = _jax()
    if isinstance(t, jax.Array) and t.is_fully_addressable:
        return t
    return np.asarray(t)


def _global_from_local(wm, local_np, extra_leading=True):
    """Stack this process's value as its row of a (nproc, ...) global array."""
    jax = _jax()
    shape = (wm.num_procs,) + tuple(local_np.shape)
    shard = jax.device_put(
        local_np[None] if extra_leading else local_np, wm.anchor_device)
    return jax.make_array_from_single_device_arrays(
        shape, wm.stacked_sharding(), [shard])


def _local_result(out):
    """Read back this process's replica of a replicated jit output."""
    return out.addressable_data(0)


# ---------------------------------------------------------------------------
# Async dispatcher: the TPU-shaped descendant of the reference's background
# thread + finalizer pool (operations.cc:557-607 RunLoopOnce,
# gpu_operations.cc:60-87 FinalizeGPUQueue). ``*_async`` entry points hand a
# staging+dispatch closure to this thread and return a handle immediately, so
# the caller (e.g. torch's autograd engine firing grad hooks) overlaps its
# backward pass with collective staging and device work. The single thread
# also guarantees one process-wide total order of eager dispatches — the SPMD
# correctness requirement the reference's rank-0 negotiation provided.
# ---------------------------------------------------------------------------

class _Dispatcher:
    def __init__(self):
        self._q: "queue.Queue" = queue.Queue()
        self._stopped = False
        # Transient-vs-fatal classification for dispatched closures:
        # connection-shaped errors (retry.is_transient) can only come from
        # the host-plane stage of a dispatch — fault injection, rendezvous
        # side channels — never from inside the SPMD program (XLA raises
        # runtime errors, which are fatal here), so retrying them locally
        # cannot desynchronize ranks. Fatal errors are NOT retried; they
        # surface via _wrap_error as HorovodInternalError so the elastic
        # loop can restore + reset instead of the handle wedging.
        self._retry = _retry.RetryPolicy.from_config()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="hvd-tpu-dispatcher")
        self._thread.start()

    def _execute(self, fn):
        return self._retry.call(fn, site="collective.dispatch")

    def submit(self, h: Handle, fn) -> None:
        h.event = threading.Event()
        if self._stopped:
            # shutdown raced with submission: fail the handle instead of
            # enqueueing to a dead thread (reference: FinalizeTensorQueue
            # flushes pending callbacks with SHUT_DOWN_ERROR)
            h.error = HorovodInternalError(
                "Horovod has been shut down; collective was not dispatched.")
            h.event.set()
            return
        if threading.current_thread() is self._thread:
            # Re-entrant submission from a dispatched closure (e.g. an
            # autotuner broadcast inside a hook): run inline — we are already
            # inside the serialized total order.
            try:
                h.result = self._execute(fn)
            except BaseException as e:  # noqa: BLE001 — surfaced at sync
                h.error = _wrap_error(e)
            finally:
                h.event.set()
            return
        # inc/dec (not set(qsize())): two threads racing absolute writes
        # can strand a stale depth; balanced atomic deltas cannot. Inc
        # BEFORE put: the dispatcher may pop and dec the instant the item
        # lands, and inc-after would let a scrape read a negative depth.
        _M_QUEUE_DEPTH.inc()
        self._q.put((h, fn))

    def run_sync(self, fn):
        """Run ``fn`` on the dispatcher thread and wait — used by collectives
        without an async variant so they stay in the single total order."""
        box = {}
        done = threading.Event()

        def wrapper():
            try:
                box["result"] = self._execute(fn)
            except BaseException as e:  # noqa: BLE001 — re-raised in caller
                box["error"] = e
            finally:
                done.set()

        if threading.current_thread() is self._thread:
            return fn()  # re-entrant call from a dispatched closure
        if self._stopped:
            raise HorovodInternalError(
                "Horovod has been shut down; collective was not dispatched.")
        _M_QUEUE_DEPTH.inc()  # before put — see submit()
        self._q.put((None, wrapper))
        done.wait()
        if "error" in box:
            raise box["error"]
        return box["result"]

    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                break  # stop() sentinel: never counted in the depth gauge
            _M_QUEUE_DEPTH.dec()
            h, fn = item
            if h is None:
                fn()  # run_sync wrapper handles its own errors
                continue
            try:
                h.result = self._execute(fn)
            except BaseException as e:  # noqa: BLE001 — surfaced at sync
                h.error = _wrap_error(e)
            finally:
                h.event.set()
        # drain anything enqueued concurrently with stop(): fail, don't hang
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return
            if item is None:
                continue
            _M_QUEUE_DEPTH.dec()
            h, fn = item
            if h is not None:
                h.error = HorovodInternalError(
                    "Horovod has been shut down; collective was not "
                    "dispatched.")
                h.event.set()
            else:
                fn()  # run_sync wrapper: unblock the waiter (fn may raise
                # inside its own try, which the wrapper converts to an error)

    def stop(self):
        self._stopped = True
        self._q.put(None)
        self._thread.join(timeout=5.0)


_dispatcher_lock = threading.Lock()


def _dispatcher(w) -> _Dispatcher:
    d = getattr(w, "dispatcher", None)
    if d is None:
        with _dispatcher_lock:
            d = getattr(w, "dispatcher", None)
            if d is None:
                d = _Dispatcher()
                w.dispatcher = d
    return d


def _response_cache(w):
    if getattr(w, "_response_cache", None) is None:
        from .response_cache import ResponseCache
        w._response_cache = ResponseCache(w.config.get(_config.CACHE_CAPACITY))
    return w._response_cache


def _check_consistency(w, wm, name, shape, dtype, kind, extra=""):
    """Cross-process metadata validation (controller.cc:378-611 analogue).

    Allgathers a 64-bit word — (exchange sequence number << 32) | metadata
    fingerprint — across processes and raises listing mismatching processes.
    Default-on (HVD_TPU_CHECK_CONSISTENCY=0 disables) in multi-process
    worlds. Steady state skips the exchange via the ResponseCache: a
    fingerprint validated once is not re-exchanged until evicted (the
    reference's cache fast path, response_cache.h:104-160).

    Divergence safety: the cache decision is per-process, so if processes
    ever submit *different* collective sequences (the only way their
    deterministic caches can diverge — a user error this check exists to
    catch), one process may skip an exchange another executes. The sequence
    number makes that mispairing a hard error on the next exchange instead of
    silent corruption: mispaired exchanges carry different seq values. A
    process that never exchanges again is caught by the stall inspector
    (stall.py), the same backstop the reference relies on for lost ranks.
    Exchanges are serialized per process (``_exchange_lock``) so concurrent
    submitter threads produce one total order.
    """
    if wm.num_procs <= 1:
        return
    if not w.config.get(_config.CHECK_CONSISTENCY):
        return
    if callable(extra):
        # grouped verbs pass their member-metadata blob lazily so the
        # (hot) disabled/single-process paths never pay the formatting
        extra = extra()
    fp = metadata_fingerprint(name, shape, dtype, kind, extra)
    cache = _response_cache(w)
    cache_key = (hash(wm.cache_key) & 0xFFFFFFFF) << 32 | fp
    with _name_lock:
        if not hasattr(w, "_consistency_lock"):
            w._consistency_lock = threading.Lock()
            w._consistency_seq = 0
    with w._consistency_lock:
        if cache.lookup(cache_key):
            _M_CONSISTENCY_CACHED.inc()
            return
        w._consistency_seq = (w._consistency_seq + 1) & 0x7FFFFFFF
        # two u32 lanes (not one u64: without jax_enable_x64, uint64 arrays
        # silently truncate to uint32)
        garr = _global_from_local(
            wm, np.array([w._consistency_seq, fp], dtype=np.uint32))

        def build():
            return _jax().jit(
                lambda a: a, out_shardings=wm.replicated_sharding())
        fn = _get_program(w, ("consistency", wm.cache_key), build)
        words = np.asarray(_local_result(fn(garr))).reshape(-1, 2)
        seqs = [int(x) for x in words[:, 0]]
        fps = [int(x) for x in words[:, 1]]
        # A joined process replays its last recorded round in lockstep with
        # active ranks (see the Join section); any mispair while replaying
        # means the active ranks' per-round collective sequence changed
        # after join() — a protocol violation worth naming precisely, since
        # the generic "different sequences" wording sends users hunting
        # for a data bug that isn't there.
        join_hint = ""
        if w.joined:
            join_hint = (
                " This process has join()ed and is replaying its last "
                f"recorded round; the mispaired entry is {name!r} ({kind}, "
                f"shape {tuple(shape)}, dtype {dtype}). The collective "
                "round pattern changed after join(): Join requires a "
                "steady per-round sequence — submit the same collectives "
                "every step and call join_round() once per step.")
        if len(set(seqs)) > 1:
            _M_CONSISTENCY_FAILED.inc()
            raise TensorValidationError(
                f"Consistency-exchange sequence mismatch at collective "
                f"{name!r} ({kind}): per-process exchange counts "
                f"{dict(enumerate(seqs))} differ, meaning processes have "
                f"submitted different collective sequences (or their "
                f"response caches diverged). All processes must submit the "
                f"same collectives in the same order." + join_hint)
        if len(set(fps)) > 1:
            _M_CONSISTENCY_FAILED.inc()
            mine = fps[wm.my_index]
            bad = [i for i, x in enumerate(fps) if x != mine]
            raise TensorValidationError(
                f"Mismatched metadata for collective {name!r} ({kind}): "
                f"processes {bad} submitted a different shape/dtype/op than "
                f"process {wm.my_index}. All processes must submit "
                f"identical requests for the same tensor name." + join_hint)
        _M_CONSISTENCY_EXCHANGED.inc()
        cache.put(cache_key)


def _combined_scale(op: ReduceOp, nproc: int, prescale: float,
                    postscale: float, dtype) -> float:
    if op in (ReduceOp.MIN, ReduceOp.MAX, ReduceOp.PRODUCT) and (
            prescale != 1.0 or postscale != 1.0):
        raise ValueError(
            "prescale_factor/postscale_factor are only supported for "
            "Sum/Average/Adasum (reference semantics).")
    scale = prescale * postscale
    if op == ReduceOp.AVERAGE:
        scale /= nproc
    if scale != 1.0 and np.issubdtype(np.dtype(dtype), np.integer):
        raise ValueError(
            "prescale/postscale/average on integer tensors is not supported; "
            "use op=horovod_tpu.Sum for integer dtypes.")
    return scale


# ---------------------------------------------------------------------------
# allreduce
# ---------------------------------------------------------------------------

def _allreduce_impl(w, values, op, prescale_factor, postscale_factor,
                    process_set=None, internal=False, meta=None):
    """Fused allreduce of a list of same-dtype-or-mixed tensors. Returns the
    list of reduced jax arrays. One jit dispatch per call (grouped tensors
    share it — the fusion-buffer behavior of collective_operations.cc:37-81,
    done by XLA fusion instead of explicit memcpy staging). ``meta`` is the
    optional ``(shapes, dtypes)`` tuple pair the async entry points already
    computed on the caller thread, so the dispatcher does not redo the
    per-member walk."""
    jnp = _jnp()
    jax = _jax()
    wm = process_set or w.world_mesh
    nproc = wm.num_procs

    if w.joined and not internal:
        # After join(), this process contributes zeros to every further
        # reduction (reference: GetTensorEntriesFromResponse substitutes zero
        # tensors for joined ranks, tensor_queue.cc).
        values = [_zeros_like_staged(v) for v in values]

    if op == ReduceOp.ADASUM:
        from .adasum import adasum_eager
        return adasum_eager(w, values, wm, prescale_factor, postscale_factor)

    # Fusion buffer, host side: grouped members that are still HOST
    # (numpy) values are packed into ONE flat buffer per dtype before
    # anything touches the device — one memcpy + one host→device transfer
    # + one program argument per dtype group instead of one per member.
    # This is the reference's MemcpyInFusionBuffer
    # (fusion_buffer_manager.h:30-55, collective_operations.cc:37-81)
    # relocated to where the bytes actually live at eager staging time.
    # Members that are already device-resident jax arrays stay separate
    # program args: host-packing those would force the readback
    # _stage_input exists to avoid. The round-4 microbenchmark measured
    # the per-member-staged grouped program at ~2x the latency of a
    # single allreduce of the same payload below 128 KB — per-member
    # device_put + N-ary dispatch, exactly the cost pre-packing
    # amortizes (MICROBENCH.json, docs/tensor-fusion.md).
    #
    # The PLAN (scales, member sizes, pack-vs-separate routing, program
    # signature) depends only on the group's metadata, which is identical
    # every training step, so it is memoized alongside the compiled
    # programs: the round-6 profile showed plan recomputation (per-member
    # _combined_scale + routing + layout sort) costing a steady-state
    # grouped dispatch ~2.5x a single allreduce's host work at 1 KiB —
    # grouping must never be a pessimization, whatever the payload.
    if meta is not None:
        shapes, dtypes = meta
    else:
        shapes = tuple(tuple(v.shape) for v in values)
        dtypes = tuple(_dtype_str(v.dtype) for v in values)
    residency = tuple(isinstance(v, jax.Array) for v in values)
    pack_cutoff = w.config.get(_config.PACK_CUTOFF)

    def build_plan():
        import math
        numels = tuple(math.prod(s) for s in shapes)
        np_dtypes = [np.dtype(dt) for dt in dtypes]
        scales = tuple(
            _combined_scale(op, nproc, prescale_factor, postscale_factor, dt)
            for dt in np_dtypes)
        # Host packing pays one extra full memcpy, so it is a win exactly
        # where transfer-count overhead dominates and a loss where
        # bandwidth does: small members pack, large members stay separate
        # (their fusion still happens in-program via concatenate, where
        # XLA overlaps the copies with the collective). The cutoff is per
        # member — a bucket of 150 small grads packs wholesale while its
        # few large conv kernels ride separately. 256 KB ≈ where the
        # round-5 CPU sweep showed the packed path's advantage fading
        # into the memcpy cost.
        host_groups: dict = {}
        separate = []
        for i in range(len(shapes)):
            if residency[i] or numels[i] * np_dtypes[i].itemsize > pack_cutoff:
                separate.append(i)
            else:
                host_groups.setdefault(dtypes[i], []).append(i)
        for dt in [d for d, idxs in host_groups.items() if len(idxs) == 1]:
            separate.append(host_groups.pop(dt)[0])  # lone member: no packing
        separate.sort()
        packed_layout = tuple(sorted(
            (dt, tuple(idxs)) for dt, idxs in host_groups.items()))
        sig_members = (packed_layout, tuple(separate), shapes, dtypes,
                       scales, op.value)
        return numels, scales, packed_layout, tuple(separate), sig_members

    numels, scales, packed_layout, separate, sig_members = _get_program(
        w, ("group_plan", shapes, dtypes, residency, op.value,
            prescale_factor, postscale_factor, pack_cutoff, nproc),
        build_plan)

    staged = [
        np.concatenate([np.ravel(values[i]) for i in idxs])
        for _dt, idxs in packed_layout
    ] + [values[i] for i in separate]
    # the program closures must capture only the PLAN (shapes/layout),
    # never `values`: cached jits live for the process lifetime and would
    # pin the first call's whole tensor list
    n_members = len(values)

    if nproc == 1:
        def build1():
            def f(*args):
                out = [None] * n_members
                k = 0
                for _dt, idxs in packed_layout:
                    buf = args[k]
                    k += 1
                    off = 0
                    for i in idxs:
                        piece = buf[off:off + numels[i]]
                        off += numels[i]
                        if scales[i] != 1.0:
                            piece = (piece * scales[i]).astype(buf.dtype)
                        out[i] = piece.reshape(shapes[i])
                for i in separate:
                    v = args[k]
                    k += 1
                    # non-unit scales on int dtypes already rejected above
                    out[i] = v if scales[i] == 1.0 \
                        else (v * scales[i]).astype(v.dtype)
                return tuple(out)
            return jax.jit(f)
        fn = _get_program(w, ("allreduce1",) + sig_members, build1)
        return list(fn(*staged))

    reducer = {
        ReduceOp.AVERAGE: jnp.sum, ReduceOp.SUM: jnp.sum,
        ReduceOp.MIN: jnp.min, ReduceOp.MAX: jnp.max,
        ReduceOp.PRODUCT: jnp.prod,
    }[op]

    sig = ("allreduce", nproc, wm.cache_key) + sig_members

    def build():
        # In-program half of the fusion buffer: each pre-packed host
        # buffer reduces as ONE cross-process collective carrying all its
        # small members; each large member gets its own collective. Large
        # members are deliberately NOT concatenated in-program: the
        # concat+slice would copy every byte twice more, and at large
        # sizes collectives are bandwidth-bound — per-launch overhead is
        # already amortized (the round-5 2-proc measurement showed the
        # concat variant ~2x slower than per-member collectives on a
        # 97 MB ResNet-50 gradient set, while for small members the
        # packed buffer is what kills the per-launch cost).
        def _reduce1(g):
            acc = g
            if g.dtype == jnp.bfloat16 or g.dtype == jnp.float16:
                acc = g.astype(jnp.float32)  # accumulate halfs in fp32
            return reducer(acc, axis=0)

        def f(*args):
            k = 0
            out = [None] * n_members
            for _dt, idxs in packed_layout:
                r = _reduce1(args[k].reshape((nproc, -1)))
                k += 1
                off = 0
                for i in idxs:
                    piece = r[off:off + numels[i]]
                    off += numels[i]
                    if scales[i] != 1.0:
                        piece = piece * scales[i]
                    out[i] = piece.reshape(shapes[i]).astype(dtypes[i])
            for i in separate:
                r = _reduce1(args[k])
                k += 1
                if scales[i] != 1.0:
                    r = r * scales[i]
                out[i] = r.astype(dtypes[i])
            return tuple(out)
        return jax.jit(f, out_shardings=wm.replicated_sharding())
    fn = _get_program(w, sig, build)

    # One batched device_put for every staged buffer: the runtime moves
    # the transfers as a group (parallel memcpy / DMA) instead of N
    # Python-sequenced ones.
    shards = jax.device_put([v[None] for v in staged], wm.anchor_device)
    globals_ = [
        jax.make_array_from_single_device_arrays(
            (nproc,) + tuple(v.shape), wm.stacked_sharding(), [sh])
        for v, sh in zip(staged, shards)]
    outs = fn(*globals_)
    if not isinstance(outs, tuple):
        outs = (outs,)
    return [_local_result(o) for o in outs]


def allreduce(tensor, average=None, name: Optional[str] = None,
              op: Optional[ReduceOp] = None, prescale_factor: float = 1.0,
              postscale_factor: float = 1.0, process_set=None):
    """Synchronous allreduce (reference: torch/mpi_ops.py:158-200,
    tensorflow/__init__.py:52-131). ``average`` is the legacy boolean knob;
    ``op`` takes precedence."""
    h = allreduce_async(tensor, average=average, name=name, op=op,
                        prescale_factor=prescale_factor,
                        postscale_factor=postscale_factor,
                        process_set=process_set)
    return synchronize(h)


def allreduce_async(tensor, average=None, name: Optional[str] = None,
                    op: Optional[ReduceOp] = None,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0, process_set=None) -> int:
    """Returns a handle immediately; staging + XLA dispatch happen on the
    dispatcher thread so the caller (e.g. autograd firing grad hooks) overlaps
    backward compute with communication (reference pipelining:
    gpu_operations.cc:60-87)."""
    op = _resolve_op(average, op)
    w = _world()
    route = _injit_route([tensor], process_set)
    if route is not None:
        # In-jit fast path: lower to the XLA collective at trace time —
        # no dispatcher, no staging, no consistency exchange — and hand
        # back an already-completed handle.
        (out,) = _injit_allreduce([tensor], op, prescale_factor,
                                  postscale_factor, route)
        _INJIT_METRICS["allreduce"].inc()
        return _injit_handle(w, name, "allreduce", out)
    name = name or _auto_name("allreduce")
    h = _table(w).begin(name, "allreduce")
    tl = w.timeline
    tl.start(name, "allreduce")
    wm = process_set or w.world_mesh
    local = _stage_input(tensor)
    try:
        # Cheap argument validation stays on the caller thread so misuse
        # raises at the call site (reference: Enqueue* rejects bad args
        # synchronously).
        _combined_scale(op, wm.num_procs, prescale_factor, postscale_factor,
                        local.dtype)
    except Exception as e:
        _finish(w, h)
        raise

    _record_round(w, ("allreduce", name, tuple(local.shape),
                      _dtype_str(local.dtype), op.value, prescale_factor,
                      postscale_factor), pset=process_set)
    # Snapshot join state at submit time: a collective submitted before
    # join() must carry real data even if the dispatcher runs it after.
    joined_at_submit = w.joined

    def dispatch():
        _check_consistency(w, wm, name, local.shape, local.dtype,
                           "allreduce", op.value)
        tl.activity_start(name, _tl.XLA_ALLREDUCE)
        vals = [_zeros_like_staged(local)] \
            if joined_at_submit else [local]
        (out,) = _allreduce_impl(w, vals, op, prescale_factor,
                                 postscale_factor, process_set, internal=True,
                                 meta=((tuple(local.shape),),
                                       (_dtype_str(local.dtype),)))
        tl.activity_end(name)
        return out

    _dispatcher(w).submit(h, _observed("allreduce", local.nbytes, dispatch))
    return _register_async(w, h)


def grouped_allreduce(tensors: Sequence, average=None,
                      name: Optional[str] = None, op: Optional[ReduceOp] = None,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0,
                      process_set=None) -> List:
    """Fused allreduce of several tensors in one dispatch (reference:
    grouped_allreduce, torch/mpi_ops.py:202-260; fusion behavior of
    EnqueueTensorAllreduces).

    Delegates to the async path so sync and async grouped reductions run
    the IDENTICAL dispatch — including the consistency exchange. The Join
    replay depends on this symmetry: a joined rank replaying a recorded
    grouped round must execute the same program sequence as active ranks
    submitting through grouped_allreduce_async, or their compiled
    collectives mispair."""
    return synchronize(grouped_allreduce_async(
        tensors, average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set))


def grouped_allreduce_async(tensors: Sequence, average=None,
                            name: Optional[str] = None,
                            op: Optional[ReduceOp] = None,
                            prescale_factor: float = 1.0,
                            postscale_factor: float = 1.0,
                            process_set=None) -> int:
    """Fused async allreduce: ONE dispatcher job and ONE handle for the
    whole group; ``synchronize(handle)`` returns the list of reduced
    tensors in input order (reference: torch/mpi_ops.py
    grouped_allreduce_async_ returns a single handle for the group).

    This is the dispatch-granularity primitive gradient bucketing rides on:
    a backward pass issues ~total_bytes/threshold of these instead of one
    dispatch per parameter (reference fusion buffer,
    collective_operations.cc:37-81)."""
    op = _resolve_op(average, op)
    w = _world()
    route = _injit_route(tensors, process_set)
    if route is not None:
        outs = _injit_allreduce(list(tensors), op, prescale_factor,
                                postscale_factor, route)
        _INJIT_METRICS["grouped_allreduce"].inc()
        return _injit_handle(w, name, "grouped_allreduce", outs)
    base = name or _auto_name("grouped_allreduce")
    h = _table(w).begin(base, "grouped_allreduce")
    tl = w.timeline
    tl.start(base, "grouped_allreduce")
    wm = process_set or w.world_mesh
    locals_ = [_stage_input(t) for t in tensors]
    try:
        # scale validity depends only on (op, factors, dtype): one check
        # per distinct dtype, not one per member — the same errors at the
        # same call sites, minus the per-member cost the round-6 grouped
        # profile flagged
        for dt in {l.dtype for l in locals_}:
            _combined_scale(op, wm.num_procs, prescale_factor,
                            postscale_factor, dt)
    except Exception:
        _finish(w, h)
        raise

    shapes = tuple(tuple(l.shape) for l in locals_)
    dtypes = tuple(_dtype_str(l.dtype) for l in locals_)
    _record_round(w, ("grouped_allreduce", base, shapes, dtypes,
                      op.value, prescale_factor, postscale_factor),
                  pset=process_set)
    joined_at_submit = w.joined

    def dispatch():
        # Wire-format shapes are flat dim lists; fingerprint the group's
        # full member metadata through the free-form ``extra`` lane —
        # including each member's staging residency and this process's
        # pack cutoff, because the hybrid fusion buffer routes by them:
        # peers whose routing diverges (e.g. one rank feeds numpy where
        # another feeds jax arrays) would compile different SPMD programs,
        # which must surface as a validation error, not a deadlock.
        routing = tuple(
            isinstance(l, _jax().Array) for l in locals_)
        cutoff = w.config.get(_config.PACK_CUTOFF)
        _check_consistency(w, wm, base, (len(locals_),), "grouped",
                           "grouped_allreduce",
                           extra=lambda: f"{shapes}|{dtypes}|{op.value}"
                                         f"|{routing}|{cutoff}")
        tl.activity_start(base, _tl.XLA_ALLREDUCE)
        vals = [_zeros_like_staged(l) for l in locals_] \
            if joined_at_submit else locals_
        outs = _allreduce_impl(w, vals, op, prescale_factor,
                               postscale_factor, process_set, internal=True,
                               meta=(shapes, dtypes))
        tl.activity_end(base)
        return outs

    _dispatcher(w).submit(h, _observed(
        "grouped_allreduce", sum(l.nbytes for l in locals_), dispatch))
    return _register_async(w, h)


# ---------------------------------------------------------------------------
# allgather
# ---------------------------------------------------------------------------

def allgather(tensor, name: Optional[str] = None, process_set=None):
    """Concatenate each process's tensor along dim 0 (reference:
    torch/mpi_ops.py:310-343). First dims may differ across processes; other
    dims must match (collective_operations.cc:87-194)."""
    h = allgather_async(tensor, name=name, process_set=process_set)
    return synchronize(h)


def allgather_async(tensor, name: Optional[str] = None, process_set=None) -> int:
    w = _world()
    route = _injit_route([tensor], process_set)
    if route is not None:
        out = _injit_allgather(tensor, route)
        _INJIT_METRICS["allgather"].inc()
        return _injit_handle(w, name, "allgather", out)
    name = name or _auto_name("allgather")
    h = _table(w).begin(name, "allgather")
    tl = w.timeline
    tl.start(name, "allgather")
    wm = process_set or w.world_mesh
    local = _stage_input(tensor)
    _record_round(w, ("allgather", name, tuple(local.shape),
                      _dtype_str(local.dtype)), pset=process_set)

    def dispatch():
        jax, jnp = _jax(), _jnp()
        nproc = wm.num_procs
        # only non-first dims must match across processes
        _check_consistency(w, wm, name, local.shape[1:], local.dtype,
                           "allgather")
        if nproc == 1:
            return jnp.asarray(local)
        tl.activity_start(name, _tl.XLA_ALLGATHER)
        # 1) exchange first-dim sizes (the reference's negotiation of
        #    per-rank sizes before allocating the allgatherv output)
        sizes = _exchange_sizes(w, wm, local.shape[0] if local.ndim else 1)
        dim0 = local.shape[0] if local.ndim else 1
        maxd = int(sizes.max())
        if all(int(s) == dim0 for s in sizes):
            # uniform fast path: global array IS the gathered result
            shape = (nproc * dim0,) + local.shape[1:]
            shard = jax.device_put(local, wm.anchor_device)
            garr = jax.make_array_from_single_device_arrays(
                shape, wm.stacked_sharding(), [shard])

            def build():
                return jax.jit(lambda a: a,
                               out_shardings=wm.replicated_sharding())
            fn = _get_program(
                w, ("allgather_uniform", nproc, wm.cache_key,
                    shape, _dtype_str(local.dtype)), build)
            result = _local_result(fn(garr))
        else:
            # ragged: pad to max, gather, slice+concat with static sizes.
            # jnp.pad keeps a device-resident jax input on device (np.pad
            # would __array__-readback exactly the staging _stage_input
            # avoids); numpy inputs land on device here either way.
            pad = maxd - dim0
            padded = jnp.pad(local,
                             [(0, pad)] + [(0, 0)] * (local.ndim - 1))
            garr = _global_from_local(wm, padded)
            sizes_t = tuple(int(s) for s in sizes)

            def build():
                def f(a):
                    parts = [a[i, :sizes_t[i]] for i in range(nproc)]
                    return jnp.concatenate(parts, axis=0)
                return jax.jit(f, out_shardings=wm.replicated_sharding())
            fn = _get_program(
                w, ("allgather_ragged", nproc, wm.cache_key, sizes_t,
                    padded.shape, _dtype_str(local.dtype)), build)
            result = _local_result(fn(garr))
        tl.activity_end(name)
        return result

    _dispatcher(w).submit(h, _observed("allgather", local.nbytes, dispatch))
    return _register_async(w, h)


def _exchange_sizes(w, wm, my_dim0: int) -> np.ndarray:
    jax = _jax()
    garr = _global_from_local(wm, np.array([my_dim0], dtype=np.int32))

    def build():
        return jax.jit(lambda a: a, out_shardings=wm.replicated_sharding())
    fn = _get_program(w, ("sizes", wm.num_procs, wm.cache_key), build)
    return np.asarray(_local_result(fn(garr))).reshape(-1)


# ---------------------------------------------------------------------------
# broadcast
# ---------------------------------------------------------------------------

def broadcast(tensor, root_rank: int, name: Optional[str] = None,
              process_set=None):
    """Every process receives root's value (reference:
    torch/mpi_ops.py:345-389). Shapes/dtypes must match on all processes
    (controller.cc validation)."""
    h = broadcast_async(tensor, root_rank, name=name, process_set=process_set)
    return synchronize(h)


def broadcast_async(tensor, root_rank: int, name: Optional[str] = None,
                    process_set=None) -> int:
    w = _world()
    route = _injit_route([tensor], process_set)
    if route is not None:
        out = _injit_broadcast(tensor, root_rank, route)
        _INJIT_METRICS["broadcast"].inc()
        return _injit_handle(w, name, "broadcast", out)
    name = name or _auto_name("broadcast")
    h = _table(w).begin(name, "broadcast")
    tl = w.timeline
    tl.start(name, "broadcast")
    wm = process_set or w.world_mesh
    nproc = wm.num_procs
    local = _stage_input(tensor)
    if not (0 <= root_rank < nproc):
        _finish(w, h)
        raise ValueError(f"root_rank {root_rank} out of range for world "
                         f"size {nproc}")
    _record_round(w, ("broadcast", name, tuple(local.shape),
                      _dtype_str(local.dtype), root_rank), pset=process_set)

    def dispatch():
        jax, jnp = _jax(), _jnp()
        _check_consistency(w, wm, name, local.shape, local.dtype,
                           "broadcast", str(root_rank))
        if nproc == 1:
            return jnp.asarray(local)
        tl.activity_start(name, _tl.XLA_BROADCAST)
        garr = _global_from_local(wm, local)

        def build():
            return jax.jit(lambda a: a[root_rank],
                           out_shardings=wm.replicated_sharding())
        fn = _get_program(
            w, ("broadcast", nproc, wm.cache_key, root_rank,
                local.shape, _dtype_str(local.dtype)), build)
        result = _local_result(fn(garr))
        tl.activity_end(name)
        return result

    _dispatcher(w).submit(h, _observed("broadcast", local.nbytes, dispatch))
    return _register_async(w, h)


def grouped_broadcast(tensors: Sequence, root_rank: int,
                      name: Optional[str] = None, process_set=None) -> List:
    """Fused broadcast of several tensors in one dispatch."""
    return synchronize(grouped_broadcast_async(
        tensors, root_rank, name=name, process_set=process_set))


def grouped_broadcast_async(tensors: Sequence, root_rank: int,
                            name: Optional[str] = None,
                            process_set=None) -> int:
    """One dispatcher job + one handle broadcasting a whole tensor list
    from ``root_rank``; ``synchronize`` returns the list in input order.
    The grouped analogue of ``broadcast_async`` — the primitive
    ``broadcast_variables`` fuses through instead of one dispatch per
    variable (reference: fused MEMCPY_IN_FUSION_BUFFER broadcasts,
    collective_operations.cc:37-81)."""
    w = _world()
    route = _injit_route(tensors, process_set)
    if route is not None:
        outs = [_injit_broadcast(t, root_rank, route) for t in tensors]
        _INJIT_METRICS["grouped_broadcast"].inc()
        return _injit_handle(w, name, "grouped_broadcast", outs)
    base = name or _auto_name("grouped_broadcast")
    h = _table(w).begin(base, "grouped_broadcast")
    tl = w.timeline
    tl.start(base, "grouped_broadcast")
    wm = process_set or w.world_mesh
    nproc = wm.num_procs
    locals_ = [_stage_input(t) for t in tensors]
    if not (0 <= root_rank < nproc):
        _finish(w, h)
        raise ValueError(f"root_rank {root_rank} out of range for world "
                         f"size {nproc}")
    shapes = tuple(tuple(l.shape) for l in locals_)
    dtypes = tuple(_dtype_str(l.dtype) for l in locals_)
    _record_round(w, ("grouped_broadcast", base, shapes, dtypes, root_rank),
                  pset=process_set)

    def dispatch():
        jax, jnp = _jax(), _jnp()
        _check_consistency(w, wm, base, (len(locals_),), "grouped",
                           "grouped_broadcast",
                           extra=lambda: f"{shapes}|{dtypes}|{root_rank}")
        if nproc == 1:
            return [jnp.asarray(l) for l in locals_]
        tl.activity_start(base, _tl.XLA_BROADCAST)

        def build():
            def f(*stacked):
                return tuple(a[root_rank] for a in stacked)
            return jax.jit(f, out_shardings=wm.replicated_sharding())
        fn = _get_program(
            w, ("grouped_broadcast", nproc, wm.cache_key, root_rank,
                shapes, dtypes), build)
        globals_ = [_global_from_local(wm, l) for l in locals_]
        outs = fn(*globals_)
        if not isinstance(outs, tuple):
            outs = (outs,)
        results = [_local_result(o) for o in outs]
        tl.activity_end(base)
        return results

    _dispatcher(w).submit(h, _observed(
        "grouped_broadcast", sum(l.nbytes for l in locals_), dispatch))
    return _register_async(w, h)


# ---------------------------------------------------------------------------
# alltoall
# ---------------------------------------------------------------------------

def alltoall(tensor, splits=None, name: Optional[str] = None, process_set=None):
    """Scatter slices of ``tensor`` to every process and gather received
    slices, concatenated along dim 0. ``splits`` (optional, len = world size)
    gives per-destination row counts; default is an even split."""
    return synchronize(alltoall_async(tensor, splits=splits, name=name,
                                      process_set=process_set))


def alltoall_async(tensor, splits=None, name: Optional[str] = None,
                   process_set=None) -> int:
    """Async alltoall returning a handle, completing the async verb set
    (reference: torch/mpi_ops.py alltoall_async; previously this verb was
    silently synchronous here — VERDICT r2 weak #6)."""
    w = _world()
    route = _injit_route([tensor], process_set)
    if route is not None:
        out = _injit_alltoall(tensor, splits, route)
        _INJIT_METRICS["alltoall"].inc()
        return _injit_handle(w, name, "alltoall", out)
    name = name or _auto_name("alltoall")
    h = _table(w).begin(name, "alltoall")
    tl = w.timeline
    tl.start(name, "alltoall")
    wm = process_set or w.world_mesh
    nproc = wm.num_procs
    jax_mod = _jax()
    staged = _stage_input(tensor)
    try:
        if splits is None:
            if staged.shape[0] % nproc != 0:
                raise ValueError(
                    f"alltoall tensor first dim {staged.shape[0]} not "
                    f"divisible by world size {nproc}; pass explicit splits")
            splits = [staged.shape[0] // nproc] * nproc
        splits = [int(s) for s in splits]
        if len(splits) != nproc or sum(splits) != staged.shape[0]:
            raise ValueError("splits must have one entry per process and sum "
                             "to the tensor's first dimension")
    except Exception:
        _finish(w, h)
        raise
    # A device-resident input with UNIFORM splits stays on device end to
    # end: pack is a reshape, unpack a slice+reshape, both shape-keyed
    # jits (VERDICT r4 weak #5 — capacity-padded MoE routing is exactly
    # this shape). Ragged splits stage through numpy deliberately: their
    # pack/unpack programs would be keyed on the split VALUES, and
    # data-dependent splits would recompile every call and grow the
    # never-evicted program cache without bound. Host inputs keep the
    # numpy pack either way. All paths run the SAME split-table exchange
    # and swap program, so mixed residency/staging across ranks stays in
    # lockstep (splits are per-rank DATA, alltoallv semantics — never
    # part of the metadata fingerprint).
    device_path = isinstance(staged, jax_mod.Array) \
        and len(set(splits)) == 1
    local = staged if device_path else np.asarray(staged)
    _record_round(w, ("alltoall", name, tuple(local.shape),
                      _dtype_str(local.dtype), tuple(splits)),
                  pset=process_set)

    def dispatch():
        jax, jnp = _jax(), _jnp()
        _check_consistency(w, wm, name, local.shape[1:], local.dtype,
                           "alltoall")
        if nproc == 1:
            return jnp.asarray(local)
        tl.activity_start(name, _tl.XLA_ALLTOALL)
        # exchange split tables so each process knows incoming sizes
        split_tbl = _exchange_split_table(w, wm, splits)
        maxs = int(split_tbl.max())
        rest = local.shape[1:]
        dt = _dtype_str(local.dtype)
        # the on-device pack requires maxs == my split (a fully uniform
        # WORLD): a ragged peer makes maxs per-call data, and a program
        # keyed on it would recompile every step — that corner drops to
        # the numpy pack below (the pre-round-5 behavior)
        if device_path and maxs == splits[0]:
            s0 = splits[0]

            def build_pack():
                def f(a):  # uniform: packing is a pure reshape
                    return jnp.reshape(a, (nproc, s0) + tuple(rest))
                return jax.jit(f)
            chunks = _get_program(
                w, ("a2a_pack", tuple(local.shape), s0, dt),
                build_pack)(local)
        else:
            # pad each outgoing chunk to maxs rows: (nproc, maxs, rest)
            src = np.asarray(local)  # one readback if device-resident
            chunks = np.zeros((nproc, maxs) + rest, dtype=src.dtype)
            off = 0
            for j, s in enumerate(splits):
                chunks[j, :s] = src[off:off + s]
                off += s
        garr = _global_from_local(wm, chunks)  # (src, dst, maxs, *rest)

        # NOTE: the jitted exchange must be IDENTICAL on every process
        # (one SPMD program); per-process unpacking happens locally below.
        def build():
            return jax.jit(lambda a: jnp.swapaxes(a, 0, 1),
                           out_shardings=wm.stacked_sharding())
        fn = _get_program(
            w, ("alltoall", nproc, wm.cache_key,
                (nproc, maxs) + tuple(rest), dt), build)
        # my shard: (1, src, maxs, *rest) — rows every src sent to me
        incoming = tuple(int(split_tbl[src, wm.my_index])
                         for src in range(nproc))
        # device unpack only in the fully uniform world (my split == maxs
        # AND every sender's too): then it is a pure shape-keyed reshape.
        # Ragged peers make `incoming`/`maxs` per-call data — jitting on
        # them would recompile every call — so that corner reads back
        # through numpy.
        if device_path and maxs == splits[0] \
                and all(i == maxs for i in incoming):
            mine = _local_result(fn(garr))  # device array

            def build_unpack():
                def f(m):
                    return jnp.reshape(m, (nproc * maxs,) + tuple(rest))
                return jax.jit(f)
            result = _get_program(
                w, ("a2a_unpack", nproc, (maxs,) + tuple(rest), dt),
                build_unpack)(mine)
        else:
            mine = np.asarray(_local_result(fn(garr)))[0]
            result = jnp.concatenate(
                [jnp.asarray(mine[s, :incoming[s]]) for s in range(nproc)],
                axis=0)
        tl.activity_end(name)
        return result

    _dispatcher(w).submit(h, _observed("alltoall", local.nbytes, dispatch))
    return _register_async(w, h)


def _exchange_split_table(w, wm, splits) -> np.ndarray:
    jax = _jax()
    garr = _global_from_local(wm, np.array(splits, dtype=np.int32))

    def build():
        return jax.jit(lambda a: a, out_shardings=wm.replicated_sharding())
    fn = _get_program(
        w, ("split_table", wm.num_procs, wm.cache_key), build)
    return np.asarray(_local_result(fn(garr))).reshape(wm.num_procs, -1)


# ---------------------------------------------------------------------------
# handles (reference: torch/mpi_ops.py poll/synchronize/join semantics)
# ---------------------------------------------------------------------------

def _register_async(w, h: Handle) -> int:
    return h.id


def _finish(w, h: Handle):
    _table(w).finish(h)


def _wrap_error(e: BaseException) -> BaseException:
    if isinstance(e, (TensorValidationError, ValueError, TypeError,
                      HorovodInternalError)):
        return e
    return HorovodInternalError(str(e))


def poll(handle: int) -> bool:
    """True when the collective backing ``handle`` has completed on device
    (reference: torch/mpi_ops.py:476-485)."""
    w = _world()
    h = _table(w).get(handle)
    if h.event is not None and not h.event.is_set():
        return False  # still queued or staging on the dispatcher thread
    if h.error is not None:
        return True
    r = h.result
    if r is None or _is_traced_result(r):
        return True
    is_ready = getattr(r, "is_ready", None)
    return bool(is_ready()) if callable(is_ready) else True


def release(handle: int) -> None:
    """Drop a COMPLETED handle without consuming its result.

    For poll-then-abandon callers: the reference's HandleManager holds a
    handle's status until wait_and_clear and simply leaks abandoned ones;
    here framework bridges reclaim them instead (torch/__init__.py caps its
    handle-metadata map and releases done-but-unconsumed handles). In-flight
    handles are left alone — finishing one early would free its name for
    reuse while the dispatcher still runs it."""
    w = _world()
    try:
        h = _table(w).get(handle)
    except ValueError:
        return
    if poll(handle):
        _finish(w, h)


def synchronize(handle: int):
    """Block until the collective completes; return its result
    (reference: torch/mpi_ops.py:487-499). The wait is interruptible by the
    stall inspector's shutdown deadline (stall_inspector.h:80 semantics):
    rather than blocking unconditionally, poll device readiness and re-check
    the deadline between polls."""
    import time as _time
    w = _world()
    h = _table(w).get(handle)
    try:
        if h.event is not None:
            # wait for the dispatcher thread, honoring the stall deadline
            insp = w.stall_inspector
            while not h.event.wait(timeout=0.05 if insp is not None else None):
                if insp is not None:
                    insp.check_shutdown()
        if h.error is not None:
            raise h.error
        r = h.result
        if r is not None and _is_traced_result(r):
            return r  # in-jit lowering: nothing device-side to wait on
        if r is not None:
            insp = w.stall_inspector
            try:
                is_ready = getattr(r, "is_ready", None)
                if insp is not None and callable(is_ready):
                    while not is_ready():
                        insp.check_shutdown()
                        _time.sleep(0.002)
                _jax().block_until_ready(r)
            except Exception as e:
                # device/runtime failures (e.g. a dead peer mid-collective)
                # must surface as HorovodInternalError so the elastic retry
                # loop can restore + reset (operations.cc:298-313 semantics)
                raise _wrap_error(e) from e
        return h.result
    finally:
        _finish(w, h)


# ---------------------------------------------------------------------------
# Join: uneven-data termination (reference Join op, operations.cc:942-966,
# controller.cc:219-273). The reference's background thread lets a joined
# rank keep negotiating one-sidedly; in the compiled SPMD plane the same
# effect comes from a ROUND protocol:
#
# * join-aware training wrappers (torch DistributedOptimizer.synchronize,
#   or user loops via join_round()) issue one tiny "round marker" allreduce
#   per step, in which every process contributes 1 if it still has data;
# * the collective layer records each round's submissions (name/shape/dtype)
#   — the wire-format Request log, the descendant of the reference's
#   negotiation messages;
# * join() flips this process to zero-contributions and REPLAYS its last
#   recorded round in lockstep with the still-active ranks until the round
#   marker reports zero active processes everywhere.
#
# This assumes steady per-round collective sequences (true for training
# loops, which is the reference's Join use case) instead of arbitrary
# dynamic sets — the static-bucketing compromise documented in SURVEY §7.
# ---------------------------------------------------------------------------

_JOIN_ROUND_NAME = "hvd.join.round"


def _record_round(w, entry, pset=None) -> None:
    # schedule ledger first (HVD_TPU_SCHEDULE_CHECK, _schedule.py): the
    # join markers are part of the cross-rank schedule even though the
    # replay log below excludes them. A no-op when the ledger is off.
    _sched.record(entry, pset)
    # request tracer (HVD_TPU_TRACE_SAMPLE, tracing.py): when the
    # submitting thread is working for a sampled request, the trace
    # gets a span naming this collective's verb + tensor name. A no-op
    # guard otherwise.
    _tracing.collective(entry)
    if entry[1].startswith(("hvd.join.", "horovod_tpu.join.")):
        return
    log = getattr(w, "_join_round_log", None)
    if log is None:
        log = w._join_round_log = []
    log.append(entry)


def join_round() -> int:
    """Round marker for cooperative Join: returns how many processes still
    have data. Training wrappers call this once per step; custom loops that
    want Join semantics must do the same."""
    w = _world()
    if w.world_mesh.num_procs == 1:
        return 0 if w.joined else 1
    me = np.zeros((1,), np.float32) if w.joined else np.ones((1,), np.float32)
    if not w.joined:
        w._join_active_rounds = getattr(w, "_join_active_rounds", 0) + 1
    out = allreduce(me, op=ReduceOp.SUM, name=_JOIN_ROUND_NAME)
    # rotate the round log: what was submitted since the last marker is one
    # full round — the replay script for join()
    w._join_last_round = getattr(w, "_join_round_log", [])
    w._join_round_log = []
    return int(round(float(np.asarray(out)[0])))


def _replay_round(entries) -> None:
    """Re-issue one round's collectives with zero/empty contributions (the
    reference's zero-tensor substitution for joined ranks,
    tensor_queue.cc GetTensorEntriesFromResponse)."""
    for e in entries:
        kind = e[0]
        if kind == "allreduce":
            _, name, shape, dtype, opv, pre, post = e
            allreduce(np.zeros(shape, dtype), op=ReduceOp(opv), name=name,
                      prescale_factor=pre, postscale_factor=post)
        elif kind == "grouped_allreduce":
            _, name, shapes, dtypes, opv, pre, post = e
            grouped_allreduce(
                [np.zeros(s, d) for s, d in zip(shapes, dtypes)],
                op=ReduceOp(opv), name=name,
                prescale_factor=pre, postscale_factor=post)
        elif kind == "allgather":
            _, name, shape, dtype = e
            # zero rows: this process contributes nothing to the gather
            allgather(np.zeros((0,) + tuple(shape[1:]), dtype), name=name)
        elif kind == "broadcast":
            _, name, shape, dtype, root = e
            broadcast(np.zeros(shape, dtype), root_rank=root, name=name)
        elif kind == "grouped_broadcast":
            _, name, shapes, dtypes, root = e
            grouped_broadcast(
                [np.zeros(s, d) for s, d in zip(shapes, dtypes)],
                root_rank=root, name=name)
        elif kind == "alltoall":
            _, name, shape, dtype, splits = e
            alltoall(np.zeros(shape, dtype), splits=splits, name=name)


def join(device: int = -1) -> int:
    """Block until every process has joined; this process contributes zeros
    to all collectives issued meanwhile (reference Join semantics). Returns
    the rank that joined last. Requires the training loop to be join-aware
    (one ``join_round()`` marker per step — the torch DistributedOptimizer
    does this automatically in multi-process worlds)."""
    w = _world()
    already = w.joined
    w.joined = True
    wm = w.world_mesh
    if wm.num_procs > 1 and not already:
        replay = list(getattr(w, "_join_last_round", []))
        # lockstep with active ranks: one replayed round + marker per their
        # real round, until nobody has data
        while True:
            _replay_round(replay)
            if join_round() == 0:
                break
    # Last to join = the process that stayed active for the most rounds
    # (wall-clock is ambiguous: every process exits the loop in the same
    # round). All processes reach this allgather together.
    rounds = np.array([getattr(w, "_join_active_rounds", 0)], np.float64)
    counts = np.asarray(allgather(rounds, name="horovod_tpu.join.ts"))
    return int(np.argmax(counts))


def joined() -> bool:
    return _world().joined


def barrier():
    """Host barrier across processes (reference: controller Barrier)."""
    allreduce(np.zeros((1,), np.float32), op=Sum, name="horovod_tpu.barrier")


def _resolve_op(average, op) -> ReduceOp:
    if average is not None and op is not None:
        raise ValueError("Set either average or op; not both "
                         "(reference semantics: util.py "
                         "get_average_backwards_compatibility_fun).")
    if op is None:
        if average is None:
            return ReduceOp.AVERAGE
        return ReduceOp.AVERAGE if average else ReduceOp.SUM
    if not isinstance(op, ReduceOp):
        raise TypeError(f"op must be a horovod_tpu.ReduceOp, got {op!r}")
    return op


# ---------------------------------------------------------------------------
# Trace-aware lowering: the in-jit fast path (ROADMAP item 2, docs/injit.md).
#
# A collective verb called with JAX tracers is already inside a compiled
# program — routing it through the dispatcher would stage tracers to the
# host (an error) and pay the eager plane's round trip, which
# MICROBENCH.json measures at 2-11x an in-jit reduce. Instead the verb
# lowers AT TRACE TIME to the XLA collective over the mapped axes in
# scope (shard_map/pmap): zero dispatcher hops, zero host staging, and
# no consistency exchange — every device runs the same compiled SPMD
# program, so the program itself is the cross-process agreement the
# eager plane's fingerprint exchange exists to establish. Eager callers
# (concrete arrays) never enter this path and keep the dispatcher
# semantics byte-for-byte.
#
# Under jit with NO mapped axis in scope (plain pjit, mode 2 of the
# optimizer), the verbs are size-1 equivalents: XLA's sharding
# propagation already supplies globally-correct values, so an extra
# reduction would double-count (the same reasoning as
# DistributedGradientTransform's mode-2 pass-through).
# ---------------------------------------------------------------------------

_TRACER_CLS = None


def _tracer_cls():
    global _TRACER_CLS
    if _TRACER_CLS is None:
        _TRACER_CLS = _jax().core.Tracer
    return _TRACER_CLS


def _injit_route(values, process_set) -> "Optional[tuple]":
    """The mapped-axis names to lower over when this call should take the
    in-jit fast path, else None for the eager dispatcher path. Empty
    tuple = traced but no mapped axis in scope (size-1 semantics)."""
    tracer = _tracer_cls()
    if not any(isinstance(v, tracer) for v in values):
        return None
    w = _world()
    if not w.config.get(_config.INJIT_FASTPATH):
        raise TypeError(
            "collective called with JAX tracers while the in-jit fast "
            "path is disabled (HVD_TPU_INJIT_FASTPATH=0). Eager "
            "collectives cannot dispatch traced values; call the verb "
            "outside jit or re-enable the fast path (docs/injit.md).")
    if process_set is not None:
        raise ValueError(
            "process_set is an eager-plane concept; under jit the "
            "collective lowers over the mesh axes in scope — scope the "
            "reduction with shard_map axis names instead.")
    return tuple(_basics.mapped_axes())


def _injit_nproc(axes) -> int:
    sizes = _basics.mapped_axis_sizes()
    n = 1
    for a in axes:
        n *= int(sizes.get(a, 1))
    return n


def _injit_handle(w, name: str, kind: str, result) -> int:
    """Completed handle for an async verb lowered at trace time, so
    handle-based callers (``*_async`` + ``synchronize``) work unchanged
    under jit. ``event`` stays None: there is nothing to wait for."""
    h = _table(w).begin(name or _auto_name(kind), kind)
    h.result = result
    return _register_async(w, h)


def _is_traced_result(r) -> bool:
    tracer = _tracer_cls()
    if isinstance(r, tracer):
        return True
    return isinstance(r, (list, tuple)) and \
        any(isinstance(x, tracer) for x in r)


def _injit_reduce_bucket(xs: list, op: ReduceOp, scale: float, axes) -> list:
    """One BUCKET of an in-jit allreduce: same-dtype leaves reduced by a
    single variadic XLA collective (psum/pmin/pmax accept tuples — the
    backend packs the fusion buffer internally; an explicit concatenate
    measured ~40x slower on the CPU sweep because XLA re-fuses the
    concat into the collective's operand). Matches the eager program's
    numerics: bf16/fp16 accumulate in fp32 (the wire stays half only
    under an explicit wire compressor — optimizer.py packed path), the
    scale applies in the accumulation dtype, the result casts back."""
    jnp = _jnp()
    lax = _jax().lax
    if op in (ReduceOp.SUM, ReduceOp.AVERAGE):
        accs = tuple(
            x.astype(jnp.float32)
            if x.dtype in (jnp.bfloat16, jnp.float16) else x for x in xs)
        rs = lax.psum(accs, axes) if axes else accs
        out = []
        for x, r in zip(xs, rs):
            if scale != 1.0:
                r = r * scale
            out.append(r.astype(x.dtype))
        return out
    if op == ReduceOp.MIN:
        return list(lax.pmin(tuple(xs), axes)) if axes else xs
    if op == ReduceOp.MAX:
        return list(lax.pmax(tuple(xs), axes)) if axes else xs
    # PRODUCT: no psum-shaped primitive — gather contributions and
    # reduce locally (small payloads; Product is a niche op).
    if not axes:
        return xs
    return [jnp.prod(lax.all_gather(x, axes, axis=0, tiled=False), axis=0)
            for x in xs]


def _injit_allreduce(values: list, op: ReduceOp, prescale: float,
                     postscale: float, axes) -> list:
    """In-jit allreduce of a member list with per-dtype packed buckets:
    same-dtype members ride ONE variadic XLA collective per
    ``fusion.packed_plan`` bucket (the compiled-plane fusion buffer —
    the backend does the buffer packing the reference's
    FusionBufferManager did by hand). All planning happens at trace
    time and is memoized on (shapes, dtypes, threshold)."""
    jnp = _jnp()
    nproc = _injit_nproc(axes)
    if op == ReduceOp.ADASUM:
        from .adasum import adasum_grads
        if len(axes) > 1:
            raise ValueError(
                "in-jit Adasum over multiple mapped axes needs an "
                "explicit hierarchy; use adasum_grads(outer_axis=..., "
                "inner_axis=...) or DistributedOptimizer(inner_axis=...).")
        out = []
        for v in values:
            g = jnp.asarray(v)
            if prescale != 1.0:
                g = g * prescale
            if axes:
                g = adasum_grads(g, outer_axis=axes[0])
            if postscale != 1.0:
                g = g * postscale
            out.append(g)
        return out
    vals = [jnp.asarray(v) for v in values]
    scales = {}
    for v in vals:
        if v.dtype not in scales:
            scales[v.dtype] = _combined_scale(
                op, nproc, prescale, postscale, v.dtype)
    # Bucket plan: per-dtype flat buffers capped at the packed threshold
    # (HVD_TPU_INJIT_PACKED_THRESHOLD, 64 MB default — the reference's
    # fusion-buffer cap). Memoized on (shapes, dtypes, threshold) in
    # fusion.py, so repeated traces of the same gradient set pay the
    # planning walk once.
    from .fusion import packed_plan
    threshold = _world().config.get(_config.INJIT_PACKED_THRESHOLD)
    plan = packed_plan([tuple(v.shape) for v in vals],
                       [v.dtype for v in vals], threshold)
    out = [None] * len(vals)
    for dt, idxs in plan:
        rs = _injit_reduce_bucket([vals[i] for i in idxs], op,
                                  scales[vals[idxs[0]].dtype], axes)
        for i, r in zip(idxs, rs):
            out[i] = r
    return out


def _injit_allgather(x, axes):
    jnp = _jnp()
    lax = _jax().lax
    x = jnp.asarray(x)
    if not axes:
        return x
    if x.ndim == 0:
        return lax.all_gather(x, axes, axis=0, tiled=False)
    return lax.all_gather(x, axes, axis=0, tiled=True)


def _injit_broadcast(x, root_rank: int, axes):
    jnp = _jnp()
    lax = _jax().lax
    x = jnp.asarray(x)
    if not axes:
        # Mode 2 (plain jit, no mapped axis): sharding propagation
        # already gives every process the same value, so broadcast is
        # the identity for ANY root the eager plane would accept — the
        # mapped-size range check (nproc == 1 here) must not reject an
        # eager-valid root_rank > 0.
        if root_rank < 0:
            raise ValueError(f"root_rank {root_rank} is negative")
        return x
    nproc = _injit_nproc(axes)
    if not (0 <= root_rank < nproc):
        raise ValueError(f"root_rank {root_rank} out of range for mapped "
                         f"axis size {nproc}")
    # all_gather + static index: XLA rewrites this to a broadcast-shaped
    # collective; root_rank indexes along the mapped axes in scope.
    return lax.all_gather(x, axes, axis=0, tiled=False)[root_rank]


def _injit_alltoall(x, splits, axes):
    jnp = _jnp()
    lax = _jax().lax
    x = jnp.asarray(x)
    nproc = _injit_nproc(axes)
    if splits is not None:
        splits = [int(s) for s in splits]
        if len(set(splits)) > 1:
            raise ValueError(
                "in-jit alltoall supports uniform splits only (ragged "
                "splits are per-rank data, which a compiled SPMD program "
                "cannot express); use the eager verb for alltoallv.")
        # same contract the eager path enforces (alltoall_async): one
        # entry per process, summing to the first dimension — otherwise
        # the lowering would silently move nproc-sized chunks instead of
        # the sizes the caller asked for.
        if len(splits) != max(nproc, 1) or sum(splits) != x.shape[0]:
            raise ValueError(
                "splits must have one entry per process and sum to the "
                f"tensor's first dimension: got {len(splits)} entries "
                f"summing to {sum(splits)} for first dim {x.shape[0]} "
                f"over mapped axis size {nproc}")
    if x.shape[0] % max(nproc, 1) != 0:
        raise ValueError(
            f"alltoall tensor first dim {x.shape[0]} not divisible by "
            f"mapped axis size {nproc}")
    if not axes:
        return x
    return lax.all_to_all(x, axes, split_axis=0, concat_axis=0, tiled=True)


# ---------------------------------------------------------------------------
# in-jit collectives: thin named wrappers for use inside shard_map/pjit.
# These are what compiled training steps call; XLA lowers them onto ICI.
# ---------------------------------------------------------------------------

def psum(x, axis_name: str):
    import jax
    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name: str):
    import jax
    return jax.lax.pmean(x, axis_name)


def all_gather_in_jit(x, axis_name: str, axis: int = 0, tiled: bool = True):
    import jax
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter_in_jit(x, axis_name: str, scatter_dimension: int = 0):
    import jax
    return jax.lax.psum_scatter(
        x, axis_name, scatter_dimension=scatter_dimension, tiled=True)


def all_to_all_in_jit(x, axis_name: str, split_axis: int, concat_axis: int):
    import jax
    return jax.lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
        tiled=True)


def ppermute(x, axis_name: str, perm):
    import jax
    return jax.lax.ppermute(x, axis_name, perm)
