"""KerasEstimator: Spark ML pipeline stage training a Keras model through
the horovod_tpu collective plane.

Reference: /root/reference/horovod/spark/keras/estimator.py:105-379 —
serialize the compiled model on the driver, materialize the DataFrame as
Parquet via the Store, train one worker per executor (DistributedOptimizer
+ initial broadcast), return a ``KerasModel`` transformer carrying the
trained weights. Round 5 adds the reference's remaining estimator depth:
``custom_objects`` (estimator.py:150 custom layer/loss resolution on the
workers), ``sample_weight_col``, and the validation-COLUMN form of
``validation`` alongside the fraction form.
"""

from typing import List, Optional

import numpy as np

from ..estimator import HorovodEstimator, HorovodModel, load_split_shard


def _serialize_keras(model):
    return {"config": model.to_json(),
            "weights": [np.array(w) for w in model.get_weights()]}


def _deserialize_keras(blob, custom_objects=None):
    import keras
    model = keras.models.model_from_json(
        blob["config"], custom_objects=custom_objects or {})
    model.set_weights(blob["weights"])
    return model


class KerasEstimator(HorovodEstimator):
    """Usage (reference recipe)::

        est = KerasEstimator(model=model, optimizer="sgd", loss="mse",
                             feature_cols=["features"], label_cols=["y"],
                             batch_size=32, epochs=4, store=store)
        keras_model = est.fit(df)            # Spark or pandas DataFrame
        pred_df = keras_model.transform(df)
    """

    _param_names: List[str] = HorovodEstimator._param_names + [
        "custom_objects",
    ]

    def _pre_fit_validate(self) -> None:
        super()._pre_fit_validate()
        if self.streaming:
            # silently materializing would hand the user the exact OOM
            # they set the flag to avoid
            raise ValueError(
                "streaming=True is implemented for TorchEstimator only; "
                "KerasEstimator materializes the worker shard in memory")

    def __init__(self, **kwargs):
        #: name -> class/function mapping shipped to workers so custom
        #: layers/losses deserialize (reference keras estimator
        #: `custom_objects`)
        self.custom_objects = None
        super().__init__(**kwargs)

    def _make_train_fn(self):
        blob = _serialize_keras(self.model)
        custom_objects = self.custom_objects
        optimizer = self.optimizer or "sgd"
        loss = self.loss or "mse"
        metrics = list(self.metrics or [])
        feature_cols = list(self.feature_cols)
        label_cols = list(self.label_cols)
        batch_size, epochs = int(self.batch_size), int(self.epochs)
        shuffle, seed = bool(self.shuffle), int(self.random_seed)
        verbose = int(self.verbose)
        validation_spec = self._validation_spec()
        sample_weight_col = self.sample_weight_col
        fs = getattr(self._resolve_store(), "fs", None)

        def train_fn(rank: int, size: int, train_path: str):
            import keras

            from ... import tensorflow as hvd_tf

            model = _deserialize_keras(blob, custom_objects)
            if size > 1:
                # initial weight broadcast (reference:
                # BroadcastGlobalVariablesCallback role)
                ws = model.get_weights()
                ws = [np.asarray(hvd_tf.broadcast(
                    _np_tensor(w), 0, name=f"keras_est.w.{i}"))
                    for i, w in enumerate(ws)]
                model.set_weights(ws)

            train, val, w_t, w_v = load_split_shard(
                train_path, feature_cols, label_cols, rank, size,
                sample_weight_col=sample_weight_col,
                validation_spec=validation_spec, fs=fs)
            x = _stack(train[:len(feature_cols)])
            y = _stack(train[len(feature_cols):])
            validation_data = None
            if val is not None:
                xv = _stack(val[:len(feature_cols)])
                yv = _stack(val[len(feature_cols):])
                validation_data = (xv, yv, w_v) if w_v is not None \
                    else (xv, yv)

            opt = (keras.optimizers.get(optimizer)
                   if isinstance(optimizer, str) else optimizer)
            if size > 1:
                opt = hvd_tf.DistributedOptimizer(opt)
            model.compile(optimizer=opt, loss=loss, metrics=metrics)
            history = model.fit(x, y, batch_size=batch_size, epochs=epochs,
                                shuffle=shuffle, verbose=verbose,
                                sample_weight=w_t,
                                validation_data=validation_data)
            return {"weights": [np.array(w) for w in model.get_weights()],
                    "history": {k: [float(v) for v in vs]
                                for k, vs in history.history.items()}}

        def _np_tensor(w):
            import tensorflow as tf
            return tf.convert_to_tensor(np.asarray(w))

        def _stack(arrays):
            out = [a.reshape(len(a), -1) if a.ndim > 1 else a
                   for a in (np.asarray(a) for a in arrays)]
            if len(out) == 1:
                a = out[0]
                return a
            return np.concatenate(
                [a.reshape(len(a), -1) for a in out], axis=1)

        return train_fn

    def _make_model(self, train_result):
        model = _deserialize_keras(_serialize_keras(self.model),
                                   self.custom_objects)
        model.set_weights(train_result["weights"])
        return KerasModel(model, self.feature_cols, self.label_cols,
                          self.output_cols,
                          history=train_result.get("history"),
                          custom_objects=self.custom_objects)


class KerasModel(HorovodModel):
    """Transformer carrying trained Keras weights (reference:
    spark/keras/estimator.py KerasModel)."""

    def __init__(self, model, feature_cols: List[str],
                 label_cols: List[str],
                 output_cols: Optional[List[str]] = None, history=None,
                 custom_objects=None):
        super().__init__(feature_cols, label_cols, output_cols)
        self.model = model
        self.history = history or {}
        self.custom_objects = custom_objects

    def getModel(self):
        return self.model

    def _predict(self, features: np.ndarray) -> np.ndarray:
        return np.asarray(self.model.predict(features, verbose=0))
