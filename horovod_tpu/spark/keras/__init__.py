"""KerasEstimator: Spark ML pipeline stage training a Keras model through
the horovod_tpu collective plane.

Reference: /root/reference/horovod/spark/keras/estimator.py:105-379 —
serialize the compiled model on the driver, materialize the DataFrame as
Parquet via the Store, train one worker per executor (DistributedOptimizer
+ initial broadcast), return a ``KerasModel`` transformer carrying the
trained weights.
"""

from typing import List, Optional

import numpy as np

from ..estimator import HorovodEstimator, HorovodModel
from ..store import read_parquet_shard


def _serialize_keras(model):
    import keras
    return {"config": model.to_json(),
            "weights": [np.array(w) for w in model.get_weights()]}


def _deserialize_keras(blob):
    import keras
    model = keras.models.model_from_json(blob["config"])
    model.set_weights(blob["weights"])
    return model


class KerasEstimator(HorovodEstimator):
    """Usage (reference recipe)::

        est = KerasEstimator(model=model, optimizer="sgd", loss="mse",
                             feature_cols=["features"], label_cols=["y"],
                             batch_size=32, epochs=4, store=store)
        keras_model = est.fit(df)            # Spark or pandas DataFrame
        pred_df = keras_model.transform(df)
    """

    def _make_train_fn(self):
        blob = _serialize_keras(self.model)
        optimizer = self.optimizer or "sgd"
        loss = self.loss or "mse"
        metrics = list(self.metrics or [])
        feature_cols = list(self.feature_cols)
        label_cols = list(self.label_cols)
        batch_size, epochs = int(self.batch_size), int(self.epochs)
        shuffle, seed = bool(self.shuffle), int(self.random_seed)
        verbose = int(self.verbose)
        validation = float(self.validation) if self.validation else 0.0

        def train_fn(rank: int, size: int, train_path: str):
            import keras

            from ... import tensorflow as hvd_tf

            model = _deserialize_keras(blob)
            if size > 1:
                # initial weight broadcast (reference:
                # BroadcastGlobalVariablesCallback role)
                ws = model.get_weights()
                ws = [np.asarray(hvd_tf.broadcast(
                    _np_tensor(w), 0, name=f"keras_est.w.{i}"))
                    for i, w in enumerate(ws)]
                model.set_weights(ws)

            cols = read_parquet_shard(
                train_path, feature_cols + label_cols, rank, size)
            x = _stack(cols[:len(feature_cols)])
            y = _stack(cols[len(feature_cols):])

            opt = (keras.optimizers.get(optimizer)
                   if isinstance(optimizer, str) else optimizer)
            if size > 1:
                opt = hvd_tf.DistributedOptimizer(opt)
            model.compile(optimizer=opt, loss=loss, metrics=metrics)
            # validation fraction held out of this worker's shard
            # (reference: estimator `validation` param, spark/common/
            # params.py — val_* metrics land in the history)
            history = model.fit(x, y, batch_size=batch_size, epochs=epochs,
                                shuffle=shuffle, verbose=verbose,
                                validation_split=validation)
            return {"weights": [np.array(w) for w in model.get_weights()],
                    "history": {k: [float(v) for v in vs]
                                for k, vs in history.history.items()}}

        def _np_tensor(w):
            import tensorflow as tf
            return tf.convert_to_tensor(np.asarray(w))

        def _stack(arrays):
            out = [a.reshape(len(a), -1) if a.ndim > 1 else a
                   for a in (np.asarray(a) for a in arrays)]
            if len(out) == 1:
                a = out[0]
                return a
            return np.concatenate(
                [a.reshape(len(a), -1) for a in out], axis=1)

        return train_fn

    def _make_model(self, train_result):
        model = _deserialize_keras(_serialize_keras(self.model))
        model.set_weights(train_result["weights"])
        return KerasModel(model, self.feature_cols, self.label_cols,
                          self.output_cols,
                          history=train_result.get("history"))


class KerasModel(HorovodModel):
    """Transformer carrying trained Keras weights (reference:
    spark/keras/estimator.py KerasModel)."""

    def __init__(self, model, feature_cols: List[str],
                 label_cols: List[str],
                 output_cols: Optional[List[str]] = None, history=None):
        super().__init__(feature_cols, label_cols, output_cols)
        self.model = model
        self.history = history or {}

    def getModel(self):
        return self.model

    def _predict(self, features: np.ndarray) -> np.ndarray:
        return np.asarray(self.model.predict(features, verbose=0))
