"""Spark cluster integration (L6 of the reference layer map).

Reference: horovod.spark (/root/reference/horovod/spark/runner.py:47-193
``run(fn)`` — a Spark job with one barrier task per executor; tasks register
with a driver service, the driver computes reachable interfaces and
launches workers that execute the pickled function; :303+ ``run_elastic``).
TPU-native redesign: Spark supplies *worker placement only* — each barrier
task becomes one horovod_tpu process wired to the driver's rendezvous
server through the same env contract the ``horovodrun-tpu`` launcher uses
(runner/exec_run.py), and the data plane remains XLA collectives. No
NIC-intersection pass is needed: the JAX coordinator address is a single
driver-chosen endpoint.

This module is import-gated: PySpark is optional exactly as the reference
gates its Spark extra (setup.py spark extra). Everything raises a clear
error without it; the pickling/topology logic is shared with the tested
``horovod_tpu.runner`` path.
"""

import os
import socket
import sys
from typing import Any, Callable, List, Optional


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
        return pyspark
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark requires pyspark, which is not installed in "
            "this environment. Install pyspark, or use horovod_tpu.runner."
            "run() / the horovodrun-tpu launcher for non-Spark clusters."
        ) from e


def run(fn: Callable, args=(), kwargs=None, num_proc: Optional[int] = None,
        env: Optional[dict] = None, verbose: bool = False) -> List[Any]:
    """Run ``fn(*args, **kwargs)`` as a distributed horovod_tpu job with
    one worker per Spark executor; returns per-rank results ordered by rank
    (reference: spark/runner.py:47-193).
    """
    _require_pyspark()
    from pyspark import BarrierTaskContext
    from pyspark.sql import SparkSession

    from ..runner.api import _dumps
    from ..runner.launch import free_port
    from ..runner.rendezvous import RendezvousServer

    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    if num_proc is None:
        num_proc = max(int(sc.defaultParallelism), 1)

    driver_host = socket.gethostname()
    server = RendezvousServer(verbose=verbose)
    port = server.start()
    # the JAX coordinator runs inside the rank-0 WORKER (executor), whose
    # host is unknown until the barrier stage runs; tasks discover it from
    # BarrierTaskContext.getTaskInfos(). The driver only fixes the port
    # number (small collision risk on the executor is retried by Spark's
    # stage retry).
    coordinator_port = free_port()
    payload = _dumps((fn, tuple(args), kwargs or {}))
    server.put("run_func", "func", payload)
    extra_env = dict(env or {})

    def task(_):
        ctx = BarrierTaskContext.get()
        rank = ctx.partitionId()
        infos = ctx.getTaskInfos()  # ordered by partition id
        hosts = [i.address.rsplit(":", 1)[0] for i in infos]
        # local/cross topology from real co-location (reference:
        # hosts.py:106-155 computes the same from the host plan)
        my_host = hosts[rank]
        same_host = [i for i, h in enumerate(hosts) if h == my_host]
        local_rank = same_host.index(rank)
        cross_hosts = sorted(set(hosts))
        os.environ.update(extra_env)
        os.environ["HVD_TPU_RANK"] = str(rank)
        os.environ["HVD_TPU_SIZE"] = str(num_proc)
        os.environ["HVD_TPU_LOCAL_RANK"] = str(local_rank)
        os.environ["HVD_TPU_LOCAL_SIZE"] = str(len(same_host))
        os.environ["HVD_TPU_CROSS_RANK"] = str(cross_hosts.index(my_host))
        os.environ["HVD_TPU_CROSS_SIZE"] = str(len(cross_hosts))
        os.environ["HVD_TPU_HOSTNAME"] = my_host
        os.environ["HVD_TPU_COORDINATOR_ADDR"] = \
            f"{hosts[0]}:{coordinator_port}"
        os.environ["HVD_TPU_RENDEZVOUS_ADDR"] = driver_host
        os.environ["HVD_TPU_RENDEZVOUS_PORT"] = str(port)
        # barrier so every executor has the env before rank 0 opens the
        # coordinator
        ctx.barrier()
        from ..runner import run_task
        result = run_task.execute_from_store(rank)
        yield rank, result

    try:
        results = (
            sc.parallelize(range(num_proc), num_proc)
            .barrier()
            .mapPartitions(task)
            .collect())
    finally:
        server.stop()
    return [r for _, r in sorted(results)]


def run_elastic(fn: Callable, args=(), kwargs=None,
                num_proc: Optional[int] = None, min_np: Optional[int] = None,
                max_np: Optional[int] = None, **launch_kwargs) -> List[Any]:
    """Elastic variant (reference: spark/runner.py:303+). Spark re-executes
    failed barrier stages; within a stage, worker failures follow the
    elastic State protocol of :mod:`horovod_tpu.elastic`."""
    _require_pyspark()
    if min_np is not None or max_np is not None:
        import logging
        logging.getLogger("horovod_tpu").warning(
            "horovod_tpu.spark.run_elastic: min_np/max_np are advisory in "
            "this release — membership changes are handled by Spark's "
            "barrier-stage retry at the requested num_proc, not by "
            "in-flight resizing. Use the horovodrun-tpu elastic launcher "
            "for true world resizing.")
    # elastic-on-spark reuses the static launch path; Spark's stage retry is
    # the outer membership mechanism
    return run(fn, args=args, kwargs=kwargs, num_proc=num_proc,
               **launch_kwargs)
