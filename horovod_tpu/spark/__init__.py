"""Spark cluster integration (L6 of the reference layer map).

Reference: horovod.spark (/root/reference/horovod/spark/runner.py:47-193
``run(fn)`` — a Spark job with one barrier task per executor; tasks register
with a driver service, the driver computes reachable interfaces and
launches workers that execute the pickled function; :303+ ``run_elastic``).
TPU-native redesign: Spark supplies *worker placement only* — each barrier
task becomes one horovod_tpu process wired to the driver's rendezvous
server through the same env contract the ``horovodrun-tpu`` launcher uses
(runner/exec_run.py), and the data plane remains XLA collectives. No
NIC-intersection pass is needed: the JAX coordinator address is a single
driver-chosen endpoint.

This module is import-gated: PySpark is optional exactly as the reference
gates its Spark extra (setup.py spark extra). Everything raises a clear
error without it; the pickling/topology logic is shared with the tested
``horovod_tpu.runner`` path.
"""

import os
import socket
import sys
from typing import Any, Callable, List, Optional


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
        return pyspark
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark requires pyspark, which is not installed in "
            "this environment. Install pyspark, or use horovod_tpu.runner."
            "run() / the horovodrun-tpu launcher for non-Spark clusters."
        ) from e


def _run_barrier_stage(fn: Callable, args, kwargs, num_proc: int,
                       extra_env: dict, verbose: bool) -> List[Any]:
    """One barrier-mode Spark stage running ``fn`` on ``num_proc`` workers
    (the body of ``run()``; also one elastic *generation* for
    ``run_elastic``)."""
    from pyspark import BarrierTaskContext
    from pyspark.sql import SparkSession

    from ..runner.api import _dumps
    from ..runner.launch import free_port
    from ..runner.rendezvous import RendezvousServer

    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext

    driver_host = socket.gethostname()
    server = RendezvousServer(verbose=verbose)
    port = server.start()
    # the JAX coordinator runs inside the rank-0 WORKER (executor), whose
    # host is unknown until the barrier stage runs; tasks discover it from
    # BarrierTaskContext.getTaskInfos(). The driver only fixes the port
    # number (small collision risk on the executor is retried by Spark's
    # stage retry).
    coordinator_port = free_port()
    payload = _dumps((fn, tuple(args), kwargs or {}))
    server.put("run_func", "func", payload)
    extra_env = dict(extra_env)

    def task(_):
        ctx = BarrierTaskContext.get()
        rank = ctx.partitionId()
        infos = ctx.getTaskInfos()  # ordered by partition id
        hosts = [i.address.rsplit(":", 1)[0] for i in infos]
        # local/cross topology from real co-location (reference:
        # hosts.py:106-155 computes the same from the host plan)
        my_host = hosts[rank]
        same_host = [i for i, h in enumerate(hosts) if h == my_host]
        local_rank = same_host.index(rank)
        cross_hosts = sorted(set(hosts))
        os.environ.update(extra_env)
        os.environ["HVD_TPU_RANK"] = str(rank)
        os.environ["HVD_TPU_SIZE"] = str(num_proc)
        os.environ["HVD_TPU_LOCAL_RANK"] = str(local_rank)
        os.environ["HVD_TPU_LOCAL_SIZE"] = str(len(same_host))
        os.environ["HVD_TPU_CROSS_RANK"] = str(cross_hosts.index(my_host))
        os.environ["HVD_TPU_CROSS_SIZE"] = str(len(cross_hosts))
        os.environ["HVD_TPU_HOSTNAME"] = my_host
        os.environ["HVD_TPU_COORDINATOR_ADDR"] = \
            f"{hosts[0]}:{coordinator_port}"
        os.environ["HVD_TPU_RENDEZVOUS_ADDR"] = driver_host
        os.environ["HVD_TPU_RENDEZVOUS_PORT"] = str(port)
        # barrier so every executor has the env before rank 0 opens the
        # coordinator
        ctx.barrier()
        from ..runner import run_task
        result = run_task.execute_from_store(rank)
        yield rank, result

    try:
        results = (
            sc.parallelize(range(num_proc), num_proc)
            .barrier()
            .mapPartitions(task)
            .collect())
    finally:
        server.stop()
    return [r for _, r in sorted(results)]


def run(fn: Callable, args=(), kwargs=None, num_proc: Optional[int] = None,
        env: Optional[dict] = None, verbose: bool = False) -> List[Any]:
    """Run ``fn(*args, **kwargs)`` as a distributed horovod_tpu job with
    one worker per Spark executor; returns per-rank results ordered by rank
    (reference: spark/runner.py:47-193).
    """
    _require_pyspark()
    if num_proc is None:
        from pyspark.sql import SparkSession
        sc = SparkSession.builder.getOrCreate().sparkContext
        num_proc = max(int(sc.defaultParallelism), 1)
    return _run_barrier_stage(fn, args, kwargs, num_proc, dict(env or {}),
                              verbose)


def _spark_available_parallelism() -> int:
    from pyspark.sql import SparkSession
    sc = SparkSession.builder.getOrCreate().sparkContext
    # live executor cores; defaultParallelism tracks registered executors
    # on dynamic-allocation clusters, so a dead executor shrinks the next
    # generation (the Spark analogue of the discovery script's host list)
    return max(int(sc.defaultParallelism), 1)


def run_elastic(fn: Callable, args=(), kwargs=None,
                num_proc: Optional[int] = None, min_np: Optional[int] = None,
                max_np: Optional[int] = None, reset_limit: int = 3,
                env: Optional[dict] = None, verbose: bool = False,
                state_dir: Optional[str] = None,
                _submit_attempt: Optional[Callable] = None,
                _available_parallelism: Optional[Callable] = None
                ) -> List[Any]:
    """Elastic training on Spark (reference: spark/runner.py:303+
    ``run_elastic``), redesigned around Spark's failure unit.

    Spark barrier stages are all-or-nothing: when one barrier task dies the
    whole stage is torn down. So a *stage attempt = one elastic
    generation*, and the elastic loop lives on the driver:

    1. every attempt sizes the world from current executor liveness,
       clamped to [min_np, max_np] (the reference's host-discovery role);
    2. workers run with the durable-commit contract of
       :mod:`horovod_tpu.elastic` (``HVD_TPU_ELASTIC_STATE_DIR`` + job id):
       every ``state.commit()`` persists, and a retried generation's
       workers restore the last commit before ``state.sync()`` — exactly
       the rank-kill recovery path of the ``horovodrun-tpu`` launcher,
       with Spark's scheduler playing the respawner;
    3. a failed attempt (barrier task death, executor loss) is retried up
       to ``reset_limit`` times (reference: --reset-limit semantics).

    ``state_dir`` must point at storage reachable by re-scheduled tasks
    (any path in local mode; shared storage on a cluster). ``fn`` should
    drive its loop through an ``hvd.elastic.State`` and ``commit()``; a
    plain fn still works but restarts from scratch on retry.

    ``_submit_attempt(num_proc, attempt_env)``/``_available_parallelism()``
    are dependency-injection points for the pyspark-free unit tests (and
    would allow other barrier schedulers to reuse the loop).
    """
    if _submit_attempt is None:
        _require_pyspark()
        submit = lambda n, e: _run_barrier_stage(  # noqa: E731
            fn, args, kwargs, n, e, verbose)
        avail = _available_parallelism or _spark_available_parallelism
    else:
        submit = _submit_attempt
        avail = _available_parallelism or (lambda: num_proc or 1)

    import logging
    import tempfile
    import uuid
    log = logging.getLogger("horovod_tpu.spark")

    min_np = int(min_np or 1)
    own_state_dir = None
    if state_dir is None:
        state_dir = own_state_dir = tempfile.mkdtemp(
            prefix="hvd_tpu_spark_elastic_")
    job_id = uuid.uuid4().hex[:12]
    base_env = dict(env or {})
    base_env["HVD_TPU_ELASTIC_STATE_DIR"] = state_dir
    base_env["HVD_TPU_ELASTIC_JOB_ID"] = job_id

    last_error: Optional[BaseException] = None
    try:
        for attempt in range(reset_limit + 1):
            live = int(avail())
            n = num_proc if attempt == 0 and num_proc else live
            if max_np:
                n = min(n, int(max_np))
            n = max(n, 1)
            if n < min_np:
                raise RuntimeError(
                    f"elastic job needs at least {min_np} workers but only "
                    f"{n} are available (attempt {attempt})")
            if attempt:
                log.warning(
                    "spark elastic: generation %d failed (%s); retrying "
                    "with %d workers", attempt - 1, last_error, n)
            try:
                return submit(n, dict(base_env))
            except Exception as e:  # noqa: BLE001 — stage/job abort
                last_error = e
        raise RuntimeError(
            f"spark elastic job failed after {reset_limit + 1} "
            f"generations") from last_error
    finally:
        if own_state_dir:
            import shutil
            shutil.rmtree(own_state_dir, ignore_errors=True)
