"""Spark ML estimator machinery shared by KerasEstimator / TorchEstimator.

Reference: /root/reference/horovod/spark/common/params.py (shared Param
plumbing), spark/common/estimator.py, and the per-framework estimators
(spark/keras/estimator.py:105-379, spark/torch/estimator.py:84-304). The
flow is identical:

  fit(df) -> materialize the DataFrame as Parquet through the Store
          -> run a distributed training function (one worker per Spark
             executor via horovod_tpu.spark.run, or in-process when no
             Spark session exists)
          -> return a Model transformer carrying the trained weights.

PySpark is optional (import-gated like the whole package): with a live
SparkSession the estimator is a real Spark ML pipeline stage (Estimator /
Model subclasses, DataFrame in/out); without it the same estimator trains
from pandas DataFrames through the identical Store/Parquet path, so the
data pipeline is exercised end-to-end either way.
"""

import os
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .store import LocalStore, Store, read_parquet_shard, write_parquet


def _pyspark():
    try:
        import pyspark
        return pyspark
    except ImportError:
        return None


def _is_spark_df(df) -> bool:
    ps = _pyspark()
    if ps is None:
        return False
    from pyspark.sql import DataFrame
    return isinstance(df, DataFrame)


class EstimatorParams:
    """Getter/setter param plumbing (reference spark/common/params.py).

    Every param ``foo`` gets ``setFoo/getFoo`` through ``_param_names`` —
    the Spark ML calling convention without requiring pyspark at import.
    """

    _param_names: List[str] = [
        "model", "optimizer", "loss", "metrics", "feature_cols",
        "label_cols", "output_cols", "batch_size", "epochs",
        "validation", "sample_weight_col", "num_proc", "store", "run_id",
        "verbose", "shuffle", "random_seed", "streaming",
        "row_group_rows",
    ]

    def __init__(self, **kwargs):
        self.model = None
        self.optimizer = None
        self.loss = None
        self.metrics = []
        self.feature_cols = ["features"]
        self.label_cols = ["label"]
        self.output_cols: Optional[List[str]] = None
        self.batch_size = 32
        self.epochs = 1
        #: float fraction in [0, 1) OR a column name (any str, even a
        #: numeric-looking one) whose rows with value > 0 form the
        #: validation set; rows with value 0 train, negative rows drop
        #: out of both sets (both reference forms,
        #: spark/common/params.py `validation`)
        self.validation = None
        #: per-row training weight column (reference `sample_weight_col`)
        self.sample_weight_col: Optional[str] = None
        self.num_proc: Optional[int] = None
        self.store: Optional[Store] = None
        self.run_id: Optional[str] = None
        self.verbose = 0
        self.shuffle = True
        self.random_seed = 0
        #: stream row groups through ParquetBatchIterator instead of
        #: materializing the shard in memory (the Petastorm reader role;
        #: datasets larger than worker RAM). Torch estimator only.
        self.streaming = False
        #: Parquet row-group size for the materialized dataset — the
        #: streaming reader's memory/shuffle granularity (smaller groups
        #: = finer shuffling and lower worker memory, more IO calls)
        self.row_group_rows = 4096
        for k, v in kwargs.items():
            if k not in self._param_names:
                raise TypeError(f"unknown estimator param {k!r}")
            setattr(self, k, v)

    def __getattr__(self, name):
        # setFooBar / getFooBar -> foo_bar  (Spark ML convention)
        for prefix in ("set", "get"):
            if name.startswith(prefix) and len(name) > 3:
                snake = "".join(
                    "_" + c.lower() if c.isupper() else c
                    for c in name[3:]).lstrip("_")
                if snake in self._param_names:
                    if prefix == "set":
                        def setter(value, _n=snake):
                            setattr(self, _n, value)
                            return self
                        return setter
                    return lambda _n=snake: getattr(self, _n)
        raise AttributeError(name)


class HorovodEstimator(EstimatorParams):
    """Common fit() machinery; subclasses provide the framework specifics
    (serialize model, remote train fn, build the Model transformer)."""

    def _resolve_store(self) -> Store:
        if self.store is None:
            import tempfile
            self.store = LocalStore(
                tempfile.mkdtemp(prefix="hvd_tpu_store_"))
        elif isinstance(self.store, str):
            self.store = Store.create(self.store)
        return self.store

    def _resolve_run_id(self) -> str:
        if not self.run_id:
            self.run_id = f"run_{int(time.time())}_{uuid.uuid4().hex[:8]}"
        return self.run_id

    # -- validation spec -----------------------------------------------------
    def _validation_spec(self):
        """('fraction', f) | ('column', name) | None — the reference's two
        `validation` forms (spark/common/params.py): a float fraction, or
        the name of a column whose rows with value > 0 are validation."""
        if self.validation is None:
            return None
        v = self.validation
        if isinstance(v, str):
            # ANY string is a column name (reference spark/common/util.py
            # check_validation) — a column literally named '0.2' must not
            # be coerced into a fraction (ADVICE r5 #1)
            return ("column", v)
        frac = float(v)
        if not 0.0 <= frac < 1.0:
            raise ValueError(
                f"validation must be a fraction in [0, 1) or a column "
                f"name, got {self.validation!r} (reference estimator "
                f"`validation` param)")
        return ("fraction", frac)

    def _extra_cols(self) -> List[str]:
        """Columns beyond features+labels that must ship in the Parquet."""
        extra = []
        spec = self._validation_spec()
        if spec and spec[0] == "column":
            extra.append(spec[1])
        if self.sample_weight_col:
            extra.append(self.sample_weight_col)
        return extra

    # -- data materialization ------------------------------------------------
    def _materialize(self, df) -> str:
        """DataFrame -> Parquet under the store; returns the dataset path."""
        store = self._resolve_store()
        path = store.get_train_data_path(self._resolve_run_id())
        cols = (list(self.feature_cols) + list(self.label_cols)
                + self._extra_cols())
        fs = getattr(store, "fs", None)
        if _is_spark_df(df):
            if int(self.row_group_rows) != 4096:
                # Spark's writer sizes row groups in BYTES
                # (parquet.block.size), not rows; this knob only shapes
                # the pandas/dict materialization path
                import logging
                logging.getLogger("horovod_tpu").warning(
                    "row_group_rows is ignored for Spark DataFrames — "
                    "configure spark.hadoop.parquet.block.size on the "
                    "session instead")
            df.select(cols).write.mode("overwrite").parquet(path)
        else:
            # pandas or dict-of-arrays
            if hasattr(df, "to_dict"):
                data = {c: np.stack(df[c].to_numpy()) if df[c].dtype == object
                        else df[c].to_numpy() for c in cols}
            else:
                data = {c: np.asarray(df[c]) for c in cols}
            write_parquet(path, data, fs=fs,
                          row_group_rows=int(self.row_group_rows))
        return path

    # -- training dispatch ---------------------------------------------------
    def _run_distributed(self, train_fn: Callable, train_path: str):
        """Run ``train_fn(rank, size, train_path)`` on every worker; returns
        rank-0's result. Uses Spark executors when a session is live,
        otherwise the current process (single worker or an existing
        horovod_tpu world)."""
        ps = _pyspark()
        if ps is not None:
            from pyspark.sql import SparkSession
            if SparkSession.getActiveSession() is not None:
                from . import run as spark_run
                results = spark_run(
                    _SparkTrainTask(train_fn, train_path),
                    num_proc=self.num_proc, verbose=bool(self.verbose))
                return results[0]
        from .. import basics
        if basics.is_initialized():
            rank, size = basics.rank(), basics.size()
        else:
            rank, size = 0, 1
        return train_fn(rank, size, train_path)

    def _pre_fit_validate(self) -> None:
        """Param validation that must run BEFORE the (possibly expensive)
        Parquet materialization. Subclasses extend (and call super)."""
        self._validation_spec()

    def fit(self, df):
        """Materialize ``df`` and train; returns the fitted Model
        transformer (reference: estimator.py fit / _fit_on_prepared_data)."""
        self._pre_fit_validate()
        train_path = self._materialize(df)
        train_fn = self._make_train_fn()
        result = self._run_distributed(train_fn, train_path)
        return self._make_model(result)

    # -- subclass hooks ------------------------------------------------------
    def _make_train_fn(self) -> Callable:
        raise NotImplementedError

    def _make_model(self, train_result):
        raise NotImplementedError


def load_split_shard(train_path: str, feature_cols: List[str],
                     label_cols: List[str], rank: int, size: int,
                     sample_weight_col: Optional[str] = None,
                     validation_spec=None, fs=None):
    """Read this worker's Parquet shard and split train/validation.

    Returns ``(train_arrays, val_arrays_or_None, w_train, w_val)`` where
    the array lists follow ``feature_cols + label_cols`` order. Implements
    both reference validation forms (spark/common/params.py): a fraction
    (tail rows of the shard) or a column whose rows with value > 0 are
    validation; plus the per-row ``sample_weight_col``.
    """
    names = list(feature_cols) + list(label_cols)
    val_col = (validation_spec[1]
               if validation_spec and validation_spec[0] == "column"
               else None)
    extra = ([sample_weight_col] if sample_weight_col else []) \
        + ([val_col] if val_col else [])
    arrays = read_parquet_shard(train_path, names + extra, rank, size,
                                fs=fs)
    data = [np.asarray(a) for a in arrays[:len(names)]]
    k = len(names)
    w = np.asarray(arrays[k], dtype=np.float32) if sample_weight_col \
        else None
    if val_col:
        col = np.asarray(arrays[-1])
        # reference semantics (spark/common/util.py _train_val_split):
        # train is col == 0 and val is col > 0, so NEGATIVE values drop
        # out of both sets — not ~(col > 0), which swept them into train
        # (ADVICE r5 #2)
        tmask = col == 0
        vmask = col > 0
        train = [a[tmask] for a in data]
        val = [a[vmask] for a in data]
        return (train, val,
                w[tmask] if w is not None else None,
                w[vmask] if w is not None else None)
    if validation_spec and validation_spec[0] == "fraction" \
            and validation_spec[1] > 0:
        n_val = int(len(data[0]) * validation_spec[1])
        if n_val:
            train = [a[:-n_val] for a in data]
            val = [a[-n_val:] for a in data]
            return (train, val,
                    w[:-n_val] if w is not None else None,
                    w[-n_val:] if w is not None else None)
    return data, None, w, None


class _SparkTrainTask:
    """Picklable wrapper so the train fn ships to Spark executors."""

    def __init__(self, fn, train_path):
        self.fn = fn
        self.train_path = train_path

    def __call__(self):
        from .. import basics
        basics.init()
        try:
            return self.fn(basics.rank(), basics.size(), self.train_path)
        finally:
            basics.shutdown()


class HorovodModel:
    """Base transformer returned by fit() (reference: spark/common —
    KerasModel/TorchModel). ``transform`` appends prediction columns."""

    def __init__(self, feature_cols: List[str], label_cols: List[str],
                 output_cols: Optional[List[str]] = None):
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.output_cols = list(output_cols) if output_cols else [
            c + "__output" for c in self.label_cols]

    def _predict(self, features: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def _stack_features(self, df, rows=None):
        cols = []
        for c in self.feature_cols:
            col = df[c]
            arr = (np.stack(col.to_numpy()) if hasattr(col, "to_numpy")
                   else np.asarray(col))
            if arr.dtype == object:
                arr = np.stack(arr)
            cols.append(arr.reshape(len(arr), -1))
        return np.concatenate(cols, axis=1) if len(cols) > 1 else cols[0]

    def transform(self, df):
        if _is_spark_df(df):
            import pandas as pd
            from pyspark.sql.functions import pandas_udf

            model = self

            @pandas_udf("array<double>")
            def predict_udf(*feature_series):
                feats = np.concatenate(
                    [np.stack(s.to_numpy()).reshape(len(s), -1)
                     for s in feature_series], axis=1)
                preds = model._predict(feats)
                return pd.Series(list(np.asarray(preds, dtype=np.float64)))

            return df.withColumn(self.output_cols[0],
                                 predict_udf(*self.feature_cols))
        out = df.copy()
        preds = np.asarray(self._predict(self._stack_features(df)))
        out[self.output_cols[0]] = list(preds)
        return out
