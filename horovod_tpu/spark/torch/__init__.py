"""TorchEstimator: Spark ML pipeline stage training a torch model through
the horovod_tpu collective plane.

Reference: /root/reference/horovod/spark/torch/estimator.py:84-304 —
pickle the model + optimizer factory on the driver, train one worker per
executor on the Store's Parquet shards with DistributedOptimizer + initial
parameter broadcast, return a ``TorchModel`` transformer.
"""

import io
from typing import Callable, List, Optional

import numpy as np

from ..estimator import HorovodEstimator, HorovodModel, load_split_shard


def _serialize_torch(model) -> bytes:
    import torch
    buf = io.BytesIO()
    torch.save(model, buf)
    return buf.getvalue()


def _deserialize_torch(blob: bytes):
    import torch
    return torch.load(io.BytesIO(blob), weights_only=False)


class TorchEstimator(HorovodEstimator):
    """Usage (reference recipe)::

        est = TorchEstimator(model=net, optimizer=lambda p: SGD(p, lr=0.1),
                             loss=torch.nn.MSELoss(),
                             feature_cols=["features"], label_cols=["y"],
                             batch_size=16, epochs=4)
        torch_model = est.fit(df)
        pred_df = torch_model.transform(df)

    ``optimizer`` is a factory ``params -> torch.optim.Optimizer`` (the
    reference passes a constructed optimizer and rebuilds it remotely; a
    factory expresses the same contract without private state surgery).
    """

    def _pre_fit_validate(self) -> None:
        super()._pre_fit_validate()
        spec = self._validation_spec()
        if self.streaming and spec and spec[0] == "fraction" \
                and spec[1] > 0:
            # a fraction split needs the shard length up front, which
            # streaming exists to avoid; the column form filters per
            # batch. Raised HERE so the user does not pay a full Parquet
            # materialization for a config error.
            raise ValueError(
                "streaming=True supports the validation COLUMN form "
                "(rows with column value > 0), not a fraction — the "
                "fraction split would require materializing the shard")

    def _make_train_fn(self):
        blob = _serialize_torch(self.model)
        opt_factory = self.optimizer
        loss_obj = self.loss
        feature_cols = list(self.feature_cols)
        label_cols = list(self.label_cols)
        batch_size, epochs = int(self.batch_size), int(self.epochs)
        shuffle, seed = bool(self.shuffle), int(self.random_seed)
        validation_spec = self._validation_spec()
        sample_weight_col = self.sample_weight_col
        fs = getattr(self._resolve_store(), "fs", None)
        streaming = bool(self.streaming)
        # metrics: fn(outputs, targets) -> scalar, evaluated per epoch on
        # the held-out set (reference: TorchEstimator metrics,
        # spark/torch/estimator.py evaluation on the val DataLoader).
        # Accepts {name: fn} or [fn, ...] (named by fn.__name__, the
        # list convention the Keras sibling uses).
        if isinstance(self.metrics, dict):
            metric_fns = dict(self.metrics)
        elif self.metrics:
            metric_fns = {}
            for i, f in enumerate(self.metrics):
                name = getattr(f, "__name__", None) or f"metric_{i}"
                if name in metric_fns or name == "<lambda>":
                    # disambiguate duplicates/lambdas instead of silently
                    # keeping only the last same-named metric
                    name = f"{name.strip('<>')}_{i}"
                metric_fns[name] = f
        else:
            metric_fns = {}

        def train_fn(rank: int, size: int, train_path: str):
            import torch

            from ... import torch as hvd_t

            model = _deserialize_torch(blob)
            loss_fn = loss_obj if loss_obj is not None else torch.nn.MSELoss()
            opt = (opt_factory(model.parameters()) if callable(opt_factory)
                   and not hasattr(opt_factory, "param_groups")
                   else opt_factory)
            if opt is None:
                opt = torch.optim.SGD(model.parameters(), lr=0.01)
            if size > 1:
                hvd_t.broadcast_parameters(model.state_dict(), root_rank=0)
                opt = hvd_t.DistributedOptimizer(
                    opt, named_parameters=model.named_parameters())

            def batch_loss(pred, target, weights):
                """Per-row weighting (reference `sample_weight_col`):
                computed through the loss's reduction='none' form, then
                weight-averaged so an all-ones column matches the
                unweighted loss exactly."""
                if weights is None:
                    return loss_fn(pred, target)
                if not hasattr(loss_fn, "reduction"):
                    raise ValueError(
                        "sample_weight_col requires a loss module with a "
                        "`reduction` attribute (torch.nn losses); got "
                        f"{type(loss_fn).__name__}")
                prev = loss_fn.reduction
                loss_fn.reduction = "none"
                try:
                    per = loss_fn(pred, target)
                finally:
                    loss_fn.reduction = prev
                per = per.reshape(len(per), -1).mean(dim=1)
                return (per * weights).sum() / weights.sum().clamp_min(
                    torch.finfo(weights.dtype).tiny)

            history = []
            val_history = []
            metrics_history = {name: [] for name in metric_fns}

            def eval_val(xv, yv):
                # eval mode: dropout off, batchnorm uses (and does not
                # update) running stats — the held-out set must not leak
                # into the shipped model. Snapshot the PRIOR mode PER
                # SUBMODULE: a user may have frozen individual layers via
                # .eval() before handing the model over, and root-level
                # train() would unfreeze them.
                modes = [(m, m.training) for m in model.modules()]
                model.eval()
                with torch.no_grad():
                    out_v = model(xv)
                    val_history.append(float(loss_fn(out_v, yv)))
                    for name, fn in metric_fns.items():
                        metrics_history[name].append(float(fn(out_v, yv)))
                for m, was_training in modes:
                    m.training = was_training

            def finish():
                state = {k: v.cpu().numpy() if hasattr(v, "cpu") else v
                         for k, v in model.state_dict().items()}
                return {"state_dict": state, "loss_history": history,
                        "val_loss_history": val_history,
                        "metrics_history": metrics_history}

            if streaming:
                # Petastorm-reader mode: row groups stream through
                # ParquetBatchIterator; memory holds one row group + one
                # batch (+ the usually-small validation subset when the
                # validation column selects one).
                #
                # Multi-process lockstep: row-group sharding gives ranks
                # UNEQUAL batch counts (unlike the in-memory rank::size
                # row split), and every opt.step() is a collective — so
                # each step first agrees via a Max-allreduce whether ANY
                # rank still has data, and a starved rank participates
                # with an explicit zero-gradient step (forward on a zero
                # batch scaled by 0.0, so the bucket hooks fire and
                # submit zeros — the Join convention, reference
                # tensor_queue.cc zero substitution).
                from ... import collectives as _coll
                from ..store import ParquetBatchIterator

                val_col = (validation_spec[1]
                           if validation_spec
                           and validation_spec[0] == "column" else None)
                extra = ([sample_weight_col] if sample_weight_col else []) \
                    + ([val_col] if val_col else [])
                it = ParquetBatchIterator(
                    train_path, feature_cols + label_cols + extra,
                    batch_size, rank, size, fs=fs, shuffle=shuffle,
                    seed=seed)
                zero_x = None

                def get_zero_x():
                    # template input for zero-grad participation; a rank
                    # can be starved an entire epoch (fewer row groups
                    # than ranks), so fall back to one template row read
                    # from the dataset itself
                    nonlocal zero_x
                    if zero_x is None:
                        t = next(iter(ParquetBatchIterator(
                            train_path, feature_cols, 1, 0, 1, fs=fs)))
                        width = _stack(
                            [t[c] for c in feature_cols]).shape[1]
                        zero_x = torch.zeros((1, width),
                                             dtype=torch.float32)
                    return zero_x

                for epoch in range(epochs):
                    it.set_epoch(epoch)
                    epoch_loss, n_rows = 0.0, 0
                    val_parts = []
                    batches = iter(it)
                    while True:
                        batch = next(batches, None)
                        while batch is not None and val_col is not None:
                            vmask = np.asarray(batch[val_col]) > 0
                            if vmask.any():
                                val_parts.append(
                                    {c: np.asarray(batch[c])[vmask]
                                     for c in feature_cols + label_cols})
                            keep = ~vmask
                            if keep.any():
                                batch = {c: np.asarray(v)[keep]
                                         for c, v in batch.items()}
                                break
                            batch = next(batches, None)  # all-val batch
                        have = batch is not None
                        if size > 1:
                            flag = _coll.allreduce(
                                np.array([1.0 if have else 0.0],
                                         np.float32),
                                op=_coll.ReduceOp.MAX,
                                name="spark_stream.have")
                            if float(np.asarray(flag)[0]) <= 0:
                                break
                        elif not have:
                            break
                        if have:
                            xb = _stack([batch[c] for c in feature_cols])
                            yb = _stack([batch[c] for c in label_cols])
                            xt = torch.from_numpy(xb.astype(np.float32))
                            if zero_x is None:
                                zero_x = torch.zeros(
                                    (1, xt.shape[1]), dtype=torch.float32)
                            yt = torch.from_numpy(yb.astype(np.float32))
                            if yt.ndim == 1:
                                yt = yt[:, None]
                            wb = None
                            if sample_weight_col:
                                wb = torch.from_numpy(np.asarray(
                                    batch[sample_weight_col], np.float32))
                            opt.zero_grad()
                            loss = batch_loss(model(xt), yt, wb)
                            loss.backward()
                            opt.step()
                            epoch_loss += float(loss.detach()) * len(xt)
                            n_rows += len(xt)
                        else:
                            # zero-grad participation runs the forward in
                            # eval mode: BatchNorm in train mode rejects
                            # a 1-row batch and would smear zeros into
                            # running stats on this rank only (buffers
                            # are not allreduced); grads are zero either
                            # way because of the * 0.0
                            modes = [(m, m.training)
                                     for m in model.modules()]
                            model.eval()
                            try:
                                opt.zero_grad()
                                (model(get_zero_x()).sum() * 0.0).backward()
                            finally:
                                for m, was in modes:
                                    m.training = was
                            opt.step()
                    history.append(epoch_loss / max(n_rows, 1))
                    if val_parts:
                        xv = torch.from_numpy(_stack([
                            np.concatenate([p[c] for p in val_parts])
                            for c in feature_cols]).astype(np.float32))
                        yv = torch.from_numpy(_stack([
                            np.concatenate([p[c] for p in val_parts])
                            for c in label_cols]).astype(np.float32))
                        if yv.ndim == 1:
                            yv = yv[:, None]
                        eval_val(xv, yv)
                return finish()

            train, val, w_t, w_v = load_split_shard(
                train_path, feature_cols, label_cols, rank, size,
                sample_weight_col=sample_weight_col,
                validation_spec=validation_spec, fs=fs)
            x = _stack(train[:len(feature_cols)]).astype(np.float32)
            y = _stack(train[len(feature_cols):]).astype(np.float32)
            xt, yt = torch.from_numpy(x), torch.from_numpy(y)
            if yt.ndim == 1:
                yt = yt[:, None]
            wt = torch.from_numpy(np.asarray(w_t, np.float32)) \
                if w_t is not None else None
            n_val = 0
            if val is not None:
                xv = torch.from_numpy(
                    _stack(val[:len(feature_cols)]).astype(np.float32))
                yv = torch.from_numpy(
                    _stack(val[len(feature_cols):]).astype(np.float32))
                if yv.ndim == 1:
                    yv = yv[:, None]
                n_val = len(xv)

            g = torch.Generator().manual_seed(seed)
            n = len(xt)
            for _ in range(epochs):
                order = (torch.randperm(n, generator=g) if shuffle
                         else torch.arange(n))
                epoch_loss = 0.0
                for s in range(0, n, batch_size):
                    idx = order[s:s + batch_size]
                    opt.zero_grad()
                    loss = batch_loss(model(xt[idx]), yt[idx],
                                      wt[idx] if wt is not None else None)
                    loss.backward()
                    opt.step()
                    epoch_loss += float(loss.detach()) * len(idx)
                history.append(epoch_loss / max(n, 1))
                if n_val:
                    eval_val(xv, yv)
            return finish()

        def _stack(arrays):
            out = [np.asarray(a) for a in arrays]
            out = [a.reshape(len(a), -1) if a.ndim > 1 else a[:, None]
                   for a in out]
            if len(out) == 1:
                return out[0]
            return np.concatenate(out, axis=1)

        return train_fn

    def _make_model(self, train_result):
        import torch
        model = _deserialize_torch(_serialize_torch(self.model))
        state = {k: torch.as_tensor(v)
                 for k, v in train_result["state_dict"].items()}
        model.load_state_dict(state)
        return TorchModel(model, self.feature_cols, self.label_cols,
                          self.output_cols,
                          loss_history=train_result.get("loss_history"),
                          val_loss_history=train_result.get(
                              "val_loss_history"),
                          metrics_history=train_result.get(
                              "metrics_history"))


class TorchModel(HorovodModel):
    """Transformer carrying the trained torch module (reference:
    spark/torch/estimator.py TorchModel)."""

    def __init__(self, model, feature_cols: List[str],
                 label_cols: List[str],
                 output_cols: Optional[List[str]] = None,
                 loss_history=None, val_loss_history=None,
                 metrics_history=None):
        super().__init__(feature_cols, label_cols, output_cols)
        self.model = model
        self.loss_history = loss_history or []
        self.val_loss_history = val_loss_history or []
        self.metrics_history = metrics_history or {}

    def getModel(self):
        return self.model

    def _predict(self, features: np.ndarray) -> np.ndarray:
        import torch
        self.model.eval()
        with torch.no_grad():
            out = self.model(torch.from_numpy(
                np.asarray(features, np.float32)))
        return out.numpy()
