"""Store abstraction for estimator data/checkpoint placement.

Reference: /root/reference/horovod/spark/common/store.py — a ``Store``
resolves run-scoped paths for intermediate training data (Parquet),
checkpoints, and logs, with filesystem-specific subclasses (LocalStore,
HDFSStore). Here the local filesystem variant is fully implemented on
pyarrow (the image's Parquet engine); remote stores (HDFS/S3/GCS) follow
the same interface and are created through :meth:`Store.create`, which
raises a clear error for schemes without a backend in this environment.

The Parquet intermediate format is the contract that lets Spark executors
(or any worker) stream train/val shards without the driver in the loop —
the role Petastorm plays in the reference (spark/keras/estimator.py:105+).
"""

import os
import shutil
from typing import List, Optional


class Store:
    """Resolves run-scoped storage paths (reference store.py Store)."""

    def get_train_data_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_val_data_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_logs_path(self, run_id: str) -> str:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def sync_fn(self, run_id: str):
        """Returns a callable that persists a local working dir into the
        store's checkpoint location (reference: store.py sync_fn)."""
        raise NotImplementedError

    @staticmethod
    def create(prefix_path: str, *args, **kwargs) -> "Store":
        """Route a path to a store backend by scheme (reference
        store.py Store.create → LocalStore | HDFSStore). Remote schemes
        (s3://, gs://, hdfs://, memory://, ...) go through fsspec when a
        backend for the scheme is installed — the HDFSStore role,
        generalized."""
        if "://" in prefix_path and not prefix_path.startswith("file://"):
            scheme = prefix_path.split("://", 1)[0]
            try:
                import fsspec
            except ImportError:
                raise ValueError(
                    f"no store backend for scheme {scheme!r}: fsspec is "
                    f"not installed; use a local path (LocalStore)")
            try:
                fsspec.get_filesystem_class(scheme)
            except ImportError as e:
                # fsspec itself is present; the SCHEME's backend package
                # (s3fs, gcsfs, ...) is what's missing — say so
                raise ValueError(
                    f"store scheme {scheme!r} needs an fsspec backend "
                    f"package: {e}")
            except ValueError as e:
                raise ValueError(
                    f"no store backend for scheme {scheme!r}: {e}")
            return FsspecStore(prefix_path, *args, **kwargs)
        return LocalStore(prefix_path.removeprefix("file://"),
                          *args, **kwargs)


class FilesystemStore(Store):
    """Shared path logic for filesystem-like stores."""

    def __init__(self, prefix_path: str,
                 train_path: Optional[str] = None,
                 val_path: Optional[str] = None,
                 checkpoint_path: Optional[str] = None):
        self.prefix_path = prefix_path
        self._train_path = train_path
        self._val_path = val_path
        self._checkpoint_path = checkpoint_path

    def _run_path(self, base: Optional[str], run_id: str, leaf: str) -> str:
        if base:
            return os.path.join(base, run_id)
        return os.path.join(self.prefix_path, "runs", run_id, leaf)

    def get_train_data_path(self, run_id: str = "") -> str:
        return self._run_path(self._train_path, run_id, "train_data")

    def get_val_data_path(self, run_id: str = "") -> str:
        return self._run_path(self._val_path, run_id, "val_data")

    def get_checkpoint_path(self, run_id: str = "") -> str:
        return self._run_path(self._checkpoint_path, run_id, "checkpoints")

    def get_logs_path(self, run_id: str = "") -> str:
        return self._run_path(None, run_id, "logs")


class LocalStore(FilesystemStore):
    """Local-filesystem store (reference store.py LocalStore)."""

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def sync_fn(self, run_id: str):
        target = self.get_checkpoint_path(run_id)

        def sync(local_dir: str) -> None:
            os.makedirs(target, exist_ok=True)
            for name in os.listdir(local_dir):
                src = os.path.join(local_dir, name)
                dst = os.path.join(target, name)
                if os.path.isdir(src):
                    shutil.copytree(src, dst, dirs_exist_ok=True)
                else:
                    shutil.copy2(src, dst)
        return sync


class FsspecStore(FilesystemStore):
    """Remote store over any fsspec filesystem — s3://, gs://, hdfs://,
    memory:// (tests) ... (reference: store.py HDFSStore, generalized to
    every scheme fsspec knows). Paths keep their scheme; the Parquet IO
    helpers route through :attr:`fs` instead of the local filesystem."""

    def __init__(self, prefix_path: str, *args, **kwargs):
        import fsspec
        # url_to_fs, not fsspec.filesystem(scheme): the URL may carry
        # host/port/credentials (hdfs://namenode:8020/..., s3://key:secret@
        # bucket/...) that scheme-only construction silently discards,
        # connecting to the default-configured endpoint instead
        # (ADVICE r5 #5)
        self.fs, _ = fsspec.core.url_to_fs(prefix_path)
        super().__init__(prefix_path, *args, **kwargs)

    def _run_path(self, base: Optional[str], run_id: str, leaf: str) -> str:
        # posix joins: remote object paths never use os.sep
        if base:
            return f"{base.rstrip('/')}/{run_id}"
        return f"{self.prefix_path.rstrip('/')}/runs/{run_id}/{leaf}"

    def exists(self, path: str) -> bool:
        return self.fs.exists(path)

    def makedirs(self, path: str) -> None:
        self.fs.makedirs(path, exist_ok=True)

    def sync_fn(self, run_id: str):
        target = self.get_checkpoint_path(run_id)
        fs = self.fs

        def sync(local_dir: str) -> None:
            fs.makedirs(target, exist_ok=True)
            fs.put(local_dir.rstrip("/") + "/", target.rstrip("/") + "/",
                   recursive=True)
        return sync


# ---------------------------------------------------------------------------
# Parquet IO helpers (the Petastorm-equivalent data path). ``fs=None``
# means the local filesystem; estimators pass ``store.fs`` so the same
# code streams local and remote datasets.
# ---------------------------------------------------------------------------

def _list_parquet_files(path: str, fs=None) -> List[str]:
    """Sorted part files of a Parquet dataset directory (shared by the
    in-memory shard reader and the streaming iterator, so both always
    see the same file set)."""
    if fs is None:
        files = sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if f.endswith(".parquet"))
    else:
        files = sorted(f for f in fs.ls(path, detail=False)
                       if f.endswith(".parquet"))
    if not files:
        raise FileNotFoundError(f"no parquet files under {path}")
    return files


def _column_to_numpy(col):
    """Arrow column -> numpy without boxing every cell: columnar
    conversion for flat types; fixed-size list columns (the vector
    encoding write_parquet uses) stack into a 2-d array."""
    import numpy as np

    arr = col.combine_chunks().to_numpy(zero_copy_only=False)
    if arr.dtype == object:
        arr = np.stack(arr)
    return arr

def write_parquet(path: str, columns: dict, row_group_rows: int = 4096,
                  partitions: int = 1, fs=None) -> None:
    """Write named numpy columns as one or more Parquet files under
    ``path`` (a directory, like a Spark parquet dataset)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    if fs is None:
        os.makedirs(path, exist_ok=True)
    else:
        fs.makedirs(path, exist_ok=True)
    n = len(next(iter(columns.values())))
    per = (n + partitions - 1) // partitions
    for p in range(partitions):
        sl = slice(p * per, min((p + 1) * per, n))
        if sl.start >= n:
            break
        arrays, names = [], []
        for name, col in columns.items():
            col = np.asarray(col)[sl]
            if col.ndim > 1:   # fixed-size vectors become list columns
                arrays.append(pa.array(list(col)))
            else:
                arrays.append(pa.array(col))
            names.append(name)
        part = f"{path.rstrip('/')}/part-{p:05d}.parquet" if fs is not None \
            else os.path.join(path, f"part-{p:05d}.parquet")
        table = pa.Table.from_arrays(arrays, names=names)
        if fs is None:
            pq.write_table(table, part, row_group_size=row_group_rows)
        else:
            with fs.open(part, "wb") as f:
                pq.write_table(table, f, row_group_size=row_group_rows)


def read_parquet_shard(path: str, columns: List[str], rank: int = 0,
                       size: int = 1, fs=None):
    """Read this worker's shard (rows ``rank::size``) of a Parquet dataset
    directory into numpy arrays, one per requested column."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    files = _list_parquet_files(path, fs)
    if fs is None:
        tables = [pq.read_table(f, columns=columns) for f in files]
    else:
        tables = []
        for f in files:
            with fs.open(f, "rb") as fh:
                tables.append(pq.read_table(fh, columns=columns))
    table = pa.concat_tables(tables)
    return [_column_to_numpy(table.column(c))[rank::size]
            for c in columns]


class ParquetBatchIterator:
    """Stream batches from a Parquet dataset directory WITHOUT
    materializing it — the Petastorm reader role (reference:
    spark/common/store.py + keras/estimator.py feed workers through
    petastorm's make_batch_reader). Sharding is by ROW GROUP round-robin
    across ranks, so a worker's memory footprint is one row group plus
    one batch regardless of dataset size.

    Yields ``{column: np.ndarray}`` dicts of up to ``batch_size`` rows;
    the final partial batch is yielded unless ``drop_last``. ``shuffle``
    permutes row-group order and rows within each row group from
    ``seed`` (new permutation per epoch via :meth:`set_epoch`, the
    torch-sampler convention).
    """

    def __init__(self, path, columns, batch_size: int, rank: int = 0,
                 size: int = 1, fs=None, shuffle: bool = False,
                 seed: int = 0, drop_last: bool = False):
        import pyarrow.parquet as pq

        self.path, self.columns = path, list(columns)
        self.batch_size, self.rank, self.size = int(batch_size), rank, size
        self.fs, self.shuffle, self.seed = fs, shuffle, int(seed)
        self.drop_last = drop_last
        self._epoch = 0
        self._files = _list_parquet_files(path, fs)
        # Row-group counts from the footers ONCE (read_metadata touches
        # only the footer); epochs then open just the files whose groups
        # this rank owns, and close them when consumed.
        self._rg_counts = []
        for f in self._files:
            if fs is None:
                self._rg_counts.append(pq.read_metadata(f).num_row_groups)
            else:
                with fs.open(f, "rb") as fh:
                    self._rg_counts.append(
                        pq.read_metadata(fh).num_row_groups)

    def set_epoch(self, epoch: int) -> None:
        self._epoch = int(epoch)

    def _open(self, f):
        """Returns (ParquetFile, closer)."""
        import pyarrow.parquet as pq
        if self.fs is None:
            pf = pq.ParquetFile(f)
            return pf, pf.close
        fh = self.fs.open(f, "rb")
        pf = pq.ParquetFile(fh)

        def close():
            pf.close()
            fh.close()
        return pf, close

    def __iter__(self):
        import numpy as np

        # global row-group list (file idx, rg idx), sharded round-robin
        groups = [(fi, g) for fi, cnt in enumerate(self._rg_counts)
                  for g in range(cnt)]
        mine = [g for i, g in enumerate(groups)
                if i % self.size == self.rank]
        rng = np.random.RandomState(self.seed + self._epoch) \
            if self.shuffle else None
        if rng is not None:
            rng.shuffle(mine)

        readers = {}   # fi -> (ParquetFile, closer), opened on demand
        remaining = {}  # fi -> groups of mine not yet consumed
        for fi, _gi in mine:
            remaining[fi] = remaining.get(fi, 0) + 1
        try:
            # chunk-list buffering: row-group arrays accumulate in a
            # list and concatenate ONCE per drain, so filling a batch
            # from k small row groups copies each row O(1) times, not
            # O(k) (quadratic pending-carry was a round-5 review find)
            parts = []      # list of dict col -> ndarray
            buffered = 0

            def drain(final: bool):
                nonlocal parts, buffered
                merged = parts[0] if len(parts) == 1 else {
                    c: np.concatenate([p[c] for p in parts])
                    for c in self.columns}
                off = 0
                while buffered - off >= self.batch_size:
                    yield {c: v[off:off + self.batch_size]
                           for c, v in merged.items()}
                    off += self.batch_size
                if final and buffered - off and not self.drop_last:
                    yield {c: v[off:] for c, v in merged.items()}
                    off = buffered
                parts = [{c: v[off:] for c, v in merged.items()}] \
                    if buffered - off else []
                buffered -= off

            for fi, gi in mine:
                if fi not in readers:
                    readers[fi] = self._open(self._files[fi])
                tbl = readers[fi][0].read_row_group(
                    gi, columns=self.columns)
                remaining[fi] -= 1
                if remaining[fi] == 0:
                    readers.pop(fi)[1]()
                cols = {c: _column_to_numpy(tbl.column(c))
                        for c in self.columns}
                n = len(next(iter(cols.values())))
                if rng is not None:
                    perm = rng.permutation(n)
                    cols = {c: v[perm] for c, v in cols.items()}
                parts.append(cols)
                buffered += n
                if buffered >= self.batch_size:
                    yield from drain(final=False)
            if buffered:
                yield from drain(final=True)
        finally:
            for _pf, close in readers.values():
                close()
