"""Environment-knob registry for horovod_tpu.

The reference drives its C++ core with ~40 ``HOROVOD_*`` environment variables
(/root/reference/horovod/common/common.h:61-88, parsed in
common/operations.cc:338-504 and common/utils/env_parser.cc). horovod_tpu keeps
the same three-layer contract (env vars <- CLI flags <- YAML config, see
runner/config_parser.py) with a typed registry so every knob is declared in
exactly one place.

Knobs use the ``HVD_TPU_`` prefix; for knobs that have a direct reference
equivalent the corresponding ``HOROVOD_*`` name is accepted as an alias so
existing run scripts keep working.
"""

import dataclasses
import os
import re
from typing import Any, Callable, Dict, Optional


def _parse_bool(v: str) -> bool:
    return v.strip().lower() in ("1", "true", "yes", "on")


@dataclasses.dataclass
class Knob:
    name: str                       # HVD_TPU_<NAME>
    default: Any
    parser: Callable[[str], Any]
    #: compatibility aliases, tried in order: a HOROVOD_* name and/or the
    #: MPI/PMIx/SLURM per-task variables (reference: gloo_context.cc reads
    #: HOROVOD_*; MPI env detection lets bare `mpirun/srun python train.py`
    #: resolve rank identity without the launcher)
    alias: "Optional[str | tuple]" = None
    help: str = ""

    def aliases(self):
        if self.alias is None:
            return ()
        return (self.alias,) if isinstance(self.alias, str) else tuple(self.alias)


_REGISTRY: Dict[str, Knob] = {}


def _register(name, default, parser, alias=None, help=""):
    _REGISTRY[name] = Knob(name, default, parser, alias, help)
    return name


# -- Fusion / cycle (reference: HOROVOD_FUSION_THRESHOLD, HOROVOD_CYCLE_TIME,
#    common.h:64-65, defaults operations.cc:417-504: 64MB / 5ms) --------------
FUSION_THRESHOLD = _register(
    "FUSION_THRESHOLD", 64 * 1024 * 1024, int, alias="HOROVOD_FUSION_THRESHOLD",
    help="Gradient-bucket fusion threshold in bytes (0 disables fusion).")
PACK_CUTOFF = _register(
    "PACK_CUTOFF", 256 * 1024, int,
    help="Grouped-collective members at or below this many bytes are packed "
         "into one host buffer per dtype before staging (one transfer per "
         "group); larger members stage separately and fuse in-program. "
         "0 disables host packing.")
CYCLE_TIME = _register(
    "CYCLE_TIME", 1.0, float, alias="HOROVOD_CYCLE_TIME",
    help="Async-coordinator cycle time in milliseconds.")
CACHE_CAPACITY = _register(
    "CACHE_CAPACITY", 1024, int, alias="HOROVOD_CACHE_CAPACITY",
    help="Capacity of the response cache (consistency-exchange "
         "fingerprints; 0 disables, reference HOROVOD_CACHE_CAPACITY).")
PROGRAM_CACHE_CAPACITY = _register(
    "PROGRAM_CACHE_CAPACITY", 1024, int,
    help="LRU bound on the compiled collective-program cache (floor 16; "
         "0 = unbounded). Distinct from CACHE_CAPACITY: program entries "
         "pin XLA executables and evictions cost a recompile on next "
         "use, so the two caches want very different capacities.")
INJIT_FASTPATH = _register(
    "INJIT_FASTPATH", True, _parse_bool,
    help="Trace-aware collective lowering: an eager collective verb "
         "(allreduce/grouped_allreduce/allgather/broadcast) called with "
         "JAX tracers — i.e. from code already under jit/shard_map — "
         "lowers directly to the XLA collective over the mapped axes in "
         "scope instead of round-tripping the host dispatcher (zero "
         "dispatcher hops, zero host staging, no consistency exchange: "
         "the compiled SPMD program is the agreement). Set 0 to make "
         "tracer inputs a hard error instead (docs/injit.md).")
INJIT_PACKED_THRESHOLD = _register(
    "INJIT_PACKED_THRESHOLD", 64 * 1024 * 1024, int,
    help="Bucket cap in bytes for the in-jit packed fusion buffers "
         "(DistributedOptimizer packing='packed'): gradient leaves are "
         "concatenated per dtype into flat buffers of at most this many "
         "bytes, one XLA collective per buffer — the compiled-plane "
         "analogue of the reference's 64 MB fusion buffer "
         "(fusion_buffer_manager.h:30-55). 0 packs each dtype into a "
         "single unbounded buffer.")

# -- Logging / timeline (reference: HOROVOD_LOG_LEVEL, HOROVOD_TIMELINE,
#    HOROVOD_TIMELINE_MARK_CYCLES, common.h:61-63) ---------------------------
LOG_LEVEL = _register(
    "LOG_LEVEL", "warning", str, alias="HOROVOD_LOG_LEVEL",
    help="trace/debug/info/warning/error/fatal.")
LOG_HIDE_TIME = _register(
    "LOG_HIDE_TIME", False, _parse_bool, alias="HOROVOD_LOG_HIDE_TIME")
TIMELINE = _register(
    "TIMELINE", "", str, alias="HOROVOD_TIMELINE",
    help="Path for chrome://tracing JSON timeline (rank 0 only).")
TIMELINE_MARK_CYCLES = _register(
    "TIMELINE_MARK_CYCLES", False, _parse_bool,
    alias="HOROVOD_TIMELINE_MARK_CYCLES")
TIMELINE_QUEUE_EVENTS = _register(
    "TIMELINE_QUEUE_EVENTS", 65536, int,
    help="Bound on the timeline/tracer record queue (records, not "
         "bytes). A slow or dead disk drops records beyond this — "
         "counted in hvd_tpu_timeline_dropped_total — instead of "
         "growing the queue without bound. 0 = unbounded (the "
         "pre-hardening behavior).")
TRACE_SAMPLE = _register(
    "TRACE_SAMPLE", 0.0, float,
    help="Head-based sampling rate for the per-request distributed "
         "tracer ([tracing](timeline.md)): the fraction of request ids "
         "traced, decided deterministically from a hash of the id so "
         "the fleet router and every replica rank make the same call "
         "with zero coordination. 0 (default) disables tracing "
         "entirely — the hot-path guard is one module-global load per "
         "call site, the timeline.py discipline. 1 traces every "
         "request.")
TRACE_DIR = _register(
    "TRACE_DIR", "", str,
    help="Directory for the tracer's per-process span files "
         "(spans-rank<N>.jsonl, one JSON span per line); `python -m "
         "tools.trace` merges all ranks' files into one cross-host "
         "chrome://tracing timeline for a request id. Unset keeps "
         "spans in the in-memory ring only (still publishable to the "
         "rendezvous 'trace' KV scope on live fleets).")

# -- Stall inspector (reference: stall_inspector.h:75-80) --------------------
STALL_CHECK_DISABLE = _register(
    "STALL_CHECK_DISABLE", False, _parse_bool,
    alias="HOROVOD_STALL_CHECK_DISABLE")
STALL_CHECK_TIME_SECONDS = _register(
    "STALL_CHECK_TIME_SECONDS", 60.0, float,
    alias="HOROVOD_STALL_CHECK_TIME_SECONDS")
STALL_SHUTDOWN_TIME_SECONDS = _register(
    "STALL_SHUTDOWN_TIME_SECONDS", 0.0, float,
    alias="HOROVOD_STALL_SHUTDOWN_TIME_SECONDS")

# -- Autotune (reference: HOROVOD_AUTOTUNE*, parameter_manager.h:33-105) -----
AUTOTUNE = _register(
    "AUTOTUNE", False, _parse_bool, alias="HOROVOD_AUTOTUNE")
AUTOTUNE_LOG = _register(
    "AUTOTUNE_LOG", "", str, alias="HOROVOD_AUTOTUNE_LOG")
AUTOTUNE_WARMUP_SAMPLES = _register(
    "AUTOTUNE_WARMUP_SAMPLES", 3, int, alias="HOROVOD_AUTOTUNE_WARMUP_SAMPLES")
AUTOTUNE_STEPS_PER_SAMPLE = _register(
    "AUTOTUNE_STEPS_PER_SAMPLE", 10, int,
    alias="HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE")
AUTOTUNE_BAYES_OPT_MAX_SAMPLES = _register(
    "AUTOTUNE_BAYES_OPT_MAX_SAMPLES", 20, int,
    alias="HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES")

# -- Rendezvous / world (reference env contract HOROVOD_RANK/SIZE/...,
#    gloo/gloo_context.cc:142-165, set by the launcher gloo_run.py:64-201) ---
RANK = _register("RANK", -1, int, alias="HOROVOD_RANK")
SIZE = _register("SIZE", -1, int, alias="HOROVOD_SIZE")
LOCAL_RANK = _register("LOCAL_RANK", -1, int, alias="HOROVOD_LOCAL_RANK")
LOCAL_SIZE = _register("LOCAL_SIZE", -1, int, alias="HOROVOD_LOCAL_SIZE")

#: External-scheduler task-identity families (reference: MPI env detection
#: that lets bare `mpirun/srun python train.py` work, docs/mpirun.rst).
#: Each row is (rank, size, local_rank, local_size) env names. A family is
#: adopted only when BOTH its rank AND size variables resolve — partial
#: hits are ignored rather than guessed, because they are actively
#: misleading: PMIX_RANK appears without any size variable on some PMIx
#: launchers, and sbatch exports SLURM_PROCID=0 to the batch step itself
#: (the per-step SLURM_STEP_NUM_TASKS guards that case: a plain batch
#: step yields size 1 = single-process, exactly the pre-detection
#: behavior). Local entries are best-effort within the adopted family.
_MPI_FAMILIES = (
    ("OMPI_COMM_WORLD_RANK", "OMPI_COMM_WORLD_SIZE",
     "OMPI_COMM_WORLD_LOCAL_RANK", "OMPI_COMM_WORLD_LOCAL_SIZE"),
    ("PMIX_RANK", "JSM_NAMESPACE_SIZE",
     "JSM_NAMESPACE_LOCAL_RANK", "JSM_NAMESPACE_LOCAL_SIZE"),
    ("SLURM_PROCID", "SLURM_STEP_NUM_TASKS",
     "SLURM_LOCALID", "SLURM_STEP_TASKS_PER_NODE"),
    # MPICH / Hydra (also Intel MPI): PMI_* identity plus MPICH's
    # per-node MPI_LOCAL* pair (reference docs/mpirun.rst lists bare
    # `mpiexec.hydra` launches; runner/mpi_run.py drives this family).
    ("PMI_RANK", "PMI_SIZE", "MPI_LOCALRANKID", "MPI_LOCALNRANKS"),
)


def mpi_task_identity(environ=None, with_source: bool = False):
    """{"RANK": r, "SIZE": n, ...} from the first coherent scheduler
    family, or {} when none applies. Shared by Config.get's fallback and
    the jsrun shim (runner/lsf.py) so the mapping lives in one place.
    ``with_source=True`` returns ``(mapping, rank_var)`` instead, so
    provenance reporting can name the scheduler variable that matched."""
    env = os.environ if environ is None else environ

    def parse(v):
        # SLURM_STEP_TASKS_PER_NODE can be "4(x2)"; take the leading int
        return int(str(v).split("(", 1)[0])

    for rank_var, size_var, lrank_var, lsize_var in _MPI_FAMILIES:
        r, s = env.get(rank_var), env.get(size_var)
        if r is None or s is None:
            continue
        try:
            out = {"RANK": parse(r), "SIZE": parse(s)}
        except ValueError:
            continue
        for key, var in (("LOCAL_RANK", lrank_var),
                         ("LOCAL_SIZE", lsize_var)):
            v = env.get(var)
            if v is not None:
                try:
                    out[key] = parse(v)
                except ValueError:
                    pass
        # MPI launchers export no cross-host identity; with host-major
        # rank placement and uniform slots (mpirun's default map-by slot
        # over -H h:n lists, and ppr mappings) the cross triple is
        # derivable: the host index and host count. Non-uniform layouts
        # stay unset rather than guessed — basics falls back to its
        # defaults there (reference: cross comm from MPI_Comm_split by
        # local_rank, mpi_context.cc:147-156). Heterogeneity shows up two
        # ways: size % local_size != 0, or a SLURM per-node list whose
        # parse() truncation would hide it ("2,4" -> 2), so any local
        # size value beyond the single "N" / uniform "N(xM)" forms also
        # disqualifies the derivation.
        ls = out.get("LOCAL_SIZE")
        raw_ls = env.get(lsize_var, "")
        uniform_form = re.fullmatch(r"\d+(\(x\d+\))?", str(raw_ls).strip())
        if ls and ls > 0 and uniform_form and out["SIZE"] % ls == 0:
            out.setdefault("CROSS_RANK", out["RANK"] // ls)
            out.setdefault("CROSS_SIZE", out["SIZE"] // ls)
        return (out, rank_var) if with_source else out
    return ({}, None) if with_source else {}
CROSS_RANK = _register("CROSS_RANK", -1, int, alias="HOROVOD_CROSS_RANK")
CROSS_SIZE = _register("CROSS_SIZE", -1, int, alias="HOROVOD_CROSS_SIZE")
HOSTNAME = _register("HOSTNAME", "", str, alias="HOROVOD_HOSTNAME")
COORDINATOR_ADDR = _register(
    "COORDINATOR_ADDR", "", str, alias="HOROVOD_GLOO_RENDEZVOUS_ADDR",
    help="host:port of the JAX distributed coordinator / rendezvous server.")
RENDEZVOUS_PORT = _register(
    "RENDEZVOUS_PORT", -1, int, alias="HOROVOD_GLOO_RENDEZVOUS_PORT",
    help="Port of the launcher's HTTP KV rendezvous server.")
RENDEZVOUS_ADDR = _register(
    "RENDEZVOUS_ADDR", "", str,
    help="Host of the launcher's HTTP KV rendezvous server.")
RENDEZVOUS_DIR = _register(
    "RENDEZVOUS_DIR", "", str,
    help="Directory for the KV rendezvous store's durable write-ahead "
         "journal + periodic snapshots. Empty (default) keeps the store "
         "in-memory only (the coordinator is then a single point of "
         "failure); set it to make the host plane crash-recoverable: a "
         "restarted coordinator replays snapshot+journal, bumps its "
         "epoch, and workers re-register instead of wedging on stale "
         "scoped keys (docs/robustness.md).")
RENDEZVOUS_SNAPSHOT_EVERY = _register(
    "RENDEZVOUS_SNAPSHOT_EVERY", 256, int,
    help="Journal appends between snapshot compactions of the rendezvous "
         "journal (HVD_TPU_RENDEZVOUS_DIR). Each compaction writes a full "
         "snapshot atomically and truncates the journal, bounding replay "
         "time after a coordinator crash. 0 disables compaction (the "
         "journal grows for the life of the job).")
ELASTIC = _register("ELASTIC", False, _parse_bool, alias="HOROVOD_ELASTIC")
ELASTIC_TIMEOUT = _register(
    "ELASTIC_TIMEOUT", 600.0, float, alias="HOROVOD_ELASTIC_TIMEOUT",
    help="Seconds the elastic driver waits for the minimum slot count "
         "before giving up (reference HOROVOD_ELASTIC_TIMEOUT).")
ELASTIC_DURABLE_COMMITS = _register(
    "ELASTIC_DURABLE_COMMITS", True, _parse_bool,
    help="Persist every elastic State.commit() to the job state dir so a "
         "hard-killed worker's respawn restores its last commit. Set 0 to "
         "skip the synchronous pickle+write for huge per-batch states "
         "(recovery then degrades to the rank-0 broadcast).")
INIT_TIMEOUT_SECONDS = _register(
    "INIT_TIMEOUT_SECONDS", 300.0, float,
    alias="HOROVOD_GLOO_TIMEOUT_SECONDS",
    help="Timeout for distributed initialization / re-rendezvous.")
HEARTBEAT_TIMEOUT_SECONDS = _register(
    "HEARTBEAT_TIMEOUT_SECONDS", -1.0, float,
    help="JAX coordination-service heartbeat timeout. Bounds how long a "
         "surviving worker blocks on a dead peer before the runtime "
         "declares the job failed. Default -1 = auto: 10s under an elastic "
         "launch (a driver exists to respawn survivors, so fast detection "
         "wins) and the jax default of 100s otherwise (no recovery path, "
         "so tolerate transient stalls). The reference's analogous knob is "
         "HOROVOD_GLOO_TIMEOUT_SECONDS, gloo_context.cc:65-68.")
SHUTDOWN_TIMEOUT_SECONDS = _register(
    "SHUTDOWN_TIMEOUT_SECONDS", 60.0, float,
    help="JAX coordination-service shutdown barrier timeout.")
HEARTBEAT_INTERVAL = _register(
    "HEARTBEAT_INTERVAL", 5.0, float,
    help="Seconds between host-plane heartbeat PUTs from each elastic "
         "worker to the rendezvous KV store (scope 'heartbeat'). 0 "
         "disables the heartbeat/liveness layer. Distinct from "
         "HVD_TPU_HEARTBEAT_TIMEOUT_SECONDS, which tunes the JAX "
         "data-plane coordination service: this layer lets the *launcher* "
         "detect a silently-hung worker (process alive, not "
         "participating) and blacklist its host without waiting for a "
         "stall deadline.")
HEARTBEAT_TIMEOUT = _register(
    "HEARTBEAT_TIMEOUT", 60.0, float,
    help="Seconds without a heartbeat after which the elastic driver "
         "declares a worker's host dead and triggers the existing "
         "blacklist -> re-rendezvous flow. Detection is bounded by "
         "timeout + one monitor poll (< 2x this value). Only armed once "
         "a worker's first beat arrives, and cleared per generation, so "
         "slow startups and re-execs are never misdeclared.")
ELASTIC_SCALE_UP_DELAY = _register(
    "ELASTIC_SCALE_UP_DELAY", 0.0, float,
    help="Seconds a grow-only membership delta must persist across "
         "discovery polls before the elastic driver interrupts the "
         "running generation to grow into the new capacity — the "
         "debounce that keeps one flapping discovery poll from "
         "triggering a resize. 0 (default) grows on the first poll "
         "(the pre-policy behavior). Shrinks (host lost or draining) "
         "always interrupt immediately.")
ELASTIC_SCALE_DOWN_POLICY = _register(
    "ELASTIC_SCALE_DOWN_POLICY", "drain", str,
    help="How the elastic driver handles a preemption notice: 'drain' "
         "(default) gracefully retires the host — final commit flushed, "
         "heartbeat tracking dropped, survivors re-rendezvous and "
         "restore its shards via resharding, host stays re-admittable — "
         "while 'immediate' fires the legacy kill path (host event -> "
         "worker exit -> FAILURE -> blacklist).")
MESH_SHAPE = _register(
    "MESH_SHAPE", "", str,
    help="Process-level parallelism mesh the elastic driver plans over, "
         "as an 'axis=size' comma list over (dp, fsdp, pp, ep, sp, tp) — "
         "e.g. 'dp=2,fsdp=2', or 'dp=-1,fsdp=2' to absorb the first "
         "generation's world size into dp. Empty (default) disables the "
         "driver's mesh plane: membership changes replan only the flat "
         "world size. When set, every generation the driver recomputes "
         "the mesh from the survivor count (MESH_RESHAPE_POLICY) and "
         "publishes it to the journaled 'mesh' rendezvous scope for "
         "workers to adopt on reset.")
MESH_RESHAPE_POLICY = _register(
    "MESH_RESHAPE_POLICY", "shrink", str,
    help="How the elastic driver re-forms the mesh when the survivor "
         "count changes: 'shrink' (default) shrinks dp first, then fsdp, "
         "never the inner pp/ep/sp/tp axes, and raises MeshShapeError "
         "when survivors don't divide into whole inner groups; 'degrade' "
         "additionally drops a remainder (whole dp replica groups' worth "
         "of capacity idles) instead of aborting; 'strict' refuses any "
         "shape change (a lost host fails the job).")

# -- Consistency checking (replaces the reference controller's per-cycle
#    dtype/shape validation, controller.cc:378-611) --------------------------
CHECK_CONSISTENCY = _register(
    "CHECK_CONSISTENCY", True, _parse_bool,
    help="Cross-process validation of name/shape/dtype for eager collectives. "
         "Default ON (the reference validates every negotiation, "
         "controller.cc:378-611); the ResponseCache makes the steady-state "
         "cost one cached lookup. Set HVD_TPU_CHECK_CONSISTENCY=0 to disable.")

# -- Metrics / telemetry (no direct reference equivalent: the reference
#    only ships Timeline + StallInspector; these knobs gate the third
#    observability pillar, metrics.py) ---------------------------------------
METRICS = _register(
    "METRICS", True, _parse_bool, alias="HOROVOD_METRICS",
    help="Enable the metrics registry (counters/gauges/histograms across "
         "the collective path). Default ON: updates are one atomic add, "
         "so unscraped metrics cost near nothing. Set HVD_TPU_METRICS=0 "
         "to make every instrumentation point a no-op.")
METRICS_PORT = _register(
    "METRICS_PORT", 0, int, alias="HOROVOD_METRICS_PORT",
    help="Port for the Prometheus text-format HTTP endpoint (GET "
         "/metrics). 0 (default) disables the endpoint; snapshots stay "
         "available via hvd.metrics_snapshot().")
METRICS_ADDR = _register(
    "METRICS_ADDR", "0.0.0.0", str, alias="HOROVOD_METRICS_ADDR",
    help="Bind address for the metrics endpoint. The default 0.0.0.0 "
         "exposes it on every interface (scraping from off-host is the "
         "point); set 127.0.0.1 on multi-tenant hosts where telemetry "
         "should stay local.")
METRICS_ALL_RANKS = _register(
    "METRICS_ALL_RANKS", False, _parse_bool,
    alias="HOROVOD_METRICS_ALL_RANKS",
    help="Serve the metrics endpoint from every process instead of rank "
         "0 only. Processes sharing a host need distinct "
         "HVD_TPU_METRICS_PORT values; a failed bind logs a warning and "
         "training continues.")

# -- Robustness: fault injection + transient-fault retry (no reference
#    equivalent — the reference can only exercise its recovery machinery
#    by actually killing processes; faults.py/retry.py make the failure
#    paths testable and survivable) -------------------------------------------
FAULT_SPEC = _register(
    "FAULT_SPEC", "", str,
    help="Deterministic fault-injection spec, ';'-separated "
         "site:kind[:param=value...] entries (e.g. "
         "'rendezvous.get:error:rate=0.3;worker.step:crash:step=12'). "
         "Empty (default) disables injection entirely; see "
         "docs/robustness.md for the grammar.")
FAULT_SEED = _register(
    "FAULT_SEED", 0, int,
    help="Seed for every probabilistic fault-injection decision. The same "
         "seed + spec + call sequence reproduces the same faults on every "
         "run and every process.")
LOCK_CHECK = _register(
    "LOCK_CHECK", False, _parse_bool,
    help="Enable the runtime lock-order sentinel: locks created through "
         "horovod_tpu/_locks.py record per-thread acquisition order and "
         "raise LockOrderError on an ordering violation (potential "
         "deadlock) or a self-deadlocking re-acquisition. Off by default "
         "(plain locks, zero overhead); the test suites run with it on. "
         "See docs/static_analysis.md.")
SCHEDULE_CHECK = _register(
    "SCHEDULE_CHECK", False, _parse_bool,
    help="Enable the runtime collective schedule ledger: every eager "
         "collective submission is fingerprinted (verb, name, dtype, "
         "rank-invariant shape, process_set) into a per-rank rolling "
         "hash published through the rendezvous KV store; on a stall "
         "deadline the per-rank ledgers are diffed and the first "
         "mismatched call site is named (e.g. \"rank 1 submitted "
         "allreduce('dense_2') where rank 0 submitted "
         "allreduce('dense_1')\") instead of a silent hang. Off by "
         "default (zero overhead); see docs/static_analysis.md.")
SDC_GUARD = _register(
    "SDC_GUARD", False, _parse_bool,
    help="Enable the silent-data-corruption step guard: every optimizer "
         "step's gradients and loss pass an all-reduced finite check "
         "plus a loss-spike EWMA bound before the update is applied. A "
         "tripped guard skips the step (retried once, then dropped), "
         "counts hvd_tpu_sdc_detections_total, and feeds the rollback/"
         "quarantine policy. Off by default (zero overhead); see "
         "docs/robustness.md.")
SDC_LOSS_SPIKE_FACTOR = _register(
    "SDC_LOSS_SPIKE_FACTOR", 10.0, float,
    help="Loss-spike bound for the SDC step guard: a finite loss "
         "exceeding factor * EWMA(|loss|) counts as a loss_spike "
         "detection. <= 0 disables the spike bound (finite checks "
         "remain).")
SDC_FINGERPRINT_EVERY = _register(
    "SDC_FINGERPRINT_EVERY", 0, int,
    help="Compare cross-replica parameter fingerprints (per-leaf bit "
         "checksum folded into one scalar) every N guarded steps, "
         "publishing each rank's value to the schedule-ledger KV scope "
         "so a divergence names the offending rank. 0 (default) "
         "disables fingerprinting.")
SDC_CONFIRM_STEPS = _register(
    "SDC_CONFIRM_STEPS", 2, int,
    help="A checkpointed step is promoted to last-good (the SDC "
         "rollback target) only after the step guard has passed this "
         "many subsequent steps — a corrupted-but-undetected step never "
         "becomes a rollback target the moment it is written.")
SDC_STRIKES = _register(
    "SDC_STRIKES", 3, int,
    help="SDC detections charged to one host within the policy window "
         "before it is reported to the elastic driver and quarantined "
         "(blacklist_host(reason='sdc'), persisted across restarts).")
RETRY_MAX_ATTEMPTS = _register(
    "RETRY_MAX_ATTEMPTS", 5, int,
    help="Total attempts (first call + retries) for transient host-plane "
         "failures (rendezvous KV ops, worker registration, dispatcher "
         "host-plane staging).")
RETRY_INITIAL_BACKOFF = _register(
    "RETRY_INITIAL_BACKOFF", 0.05, float,
    help="Base backoff in seconds; retry k sleeps uniform(0, "
         "min(RETRY_MAX_BACKOFF, RETRY_INITIAL_BACKOFF * 2**(k-1))) "
         "(capped exponential backoff with full jitter).")
RETRY_MAX_BACKOFF = _register(
    "RETRY_MAX_BACKOFF", 2.0, float,
    help="Upper bound in seconds on any single retry backoff.")
RETRY_DEADLINE = _register(
    "RETRY_DEADLINE", 60.0, float,
    help="Overall per-call retry budget in seconds; a retry that would "
         "overrun it surfaces the last error instead of sleeping.")

# -- Checkpointing (no reference equivalent — the reference delegates to
#    rank-0 framework checkpoints; checkpointing/ is the TPU-pod-scale
#    subsystem: async snapshot-then-persist, sharded writes, manifests) ------
CHECKPOINT_MAX_INFLIGHT = _register(
    "CHECKPOINT_MAX_INFLIGHT", 2, int,
    help="Bound on async checkpoint saves snapshotted but not yet "
         "persisted. A training loop that outruns storage blocks in "
         "save() once the queue is full (backpressure) instead of "
         "accumulating unbounded host-RAM copies of the model.")
CHECKPOINT_KEEP = _register(
    "CHECKPOINT_KEEP", 0, int,
    help="Retention GC: keep the last N completed checkpoint steps, "
         "deleting superseded ones from the background writer after "
         "each commit. 0 (default) keeps everything. Composes with "
         "HVD_TPU_CHECKPOINT_KEEP_PERIOD (a step survives if either "
         "rule wants it); the newest step always survives.")
CHECKPOINT_KEEP_PERIOD = _register(
    "CHECKPOINT_KEEP_PERIOD", 0, int,
    help="Retention GC: steps divisible by this period are kept forever "
         "(milestone checkpoints for offline eval), regardless of "
         "HVD_TPU_CHECKPOINT_KEEP. 0 (default) disables the rule.")

# -- Inference serving (no reference equivalent — the reference stops at
#    training; serving/ is the request-to-batch inference plane: dynamic
#    micro-batching, admission control, checkpoint hot-reload) ---------------
SERVING_MAX_BATCH = _register(
    "SERVING_MAX_BATCH", 8, int,
    help="Largest micro-batch (rows) the serving batcher coalesces "
         "concurrent requests into — the top shape bucket, so it bounds "
         "both latency amortization and the padded-forward cost. Must "
         "cover the largest single request.")
SERVING_BATCH_TIMEOUT_MS = _register(
    "SERVING_BATCH_TIMEOUT_MS", 5.0, float,
    help="Milliseconds the batcher holds an open micro-batch waiting for "
         "more requests before dispatching it. The latency/throughput "
         "dial: 0 dispatches every request alone (lowest latency, no "
         "coalescing), larger values fill bigger buckets under load.")
SERVING_BUCKETS = _register(
    "SERVING_BUCKETS", "", str,
    help="Comma-separated static batch-shape buckets (rows) the serving "
         "batcher pads micro-batches to, e.g. '1,2,4,8'. Compiled SPMD "
         "forwards need static shapes; each bucket costs one compile "
         "(cached, optionally warmed). Empty (default) = powers of two "
         "up to HVD_TPU_SERVING_MAX_BATCH.")
SERVING_QUEUE_DEPTH = _register(
    "SERVING_QUEUE_DEPTH", 64, int,
    help="Admission control: bound on requests queued ahead of the "
         "serving batcher. A request arriving at a full queue is "
         "rejected immediately (HTTP 503) instead of growing an "
         "unbounded backlog every queued request would time out in — "
         "overload degrades to fast backpressure, not collapse.")
SERVING_DEADLINE_MS = _register(
    "SERVING_DEADLINE_MS", 2000.0, float,
    help="Default per-request deadline in milliseconds (callers can set "
         "a per-request value). A request whose deadline expires before "
         "its micro-batch is formed is answered HTTP 429 without "
         "touching the device; expiry checks happen at admission and "
         "at batch formation. 0 disables deadlines.")
SERVING_PORT = _register(
    "SERVING_PORT", 0, int,
    help="Port for the inference HTTP front-end (POST /v1/infer, GET "
         "/healthz). 0 (default) binds an ephemeral port (the server "
         "reports it); the engine API works without the HTTP layer.")
SERVING_RELOAD_POLL_SECONDS = _register(
    "SERVING_RELOAD_POLL_SECONDS", 10.0, float,
    help="Seconds between checkpoint-directory polls for serving "
         "hot-reload: when latest_step() moves past the serving step, "
         "the engine restores the new step in the background and "
         "atomically swaps it in without dropping in-flight requests. "
         "0 disables polling (hot-reload stays available via "
         "InferenceEngine.reload()).")
SERVING_WARMUP = _register(
    "SERVING_WARMUP", True, _parse_bool,
    help="Compile every serving shape bucket at engine start with "
         "zero-filled inputs, so no live request pays an XLA compile. "
         "Set 0 to trade first-request latency for faster startup.")

# -- Generation serving (no reference equivalent — the continuous-batching
#    decode plane, serving/generation/: paged KV cache + iteration-level
#    scheduling for autoregressive models) ------------------------------------
GEN_BLOCK_SIZE = _register(
    "GEN_BLOCK_SIZE", 16, int,
    help="Tokens per KV-cache block in the paged generation cache. "
         "Smaller blocks track live tokens tighter (less padding waste "
         "per sequence, at most block_size-1 slots); larger blocks mean "
         "fewer allocator operations and block-table entries. The "
         "compiled decode program gathers max_seq_len/block_size blocks "
         "per sequence, so the product with HVD_TPU_GEN_NUM_BLOCKS is "
         "the pool's token capacity.")
GEN_NUM_BLOCKS = _register(
    "GEN_NUM_BLOCKS", 512, int,
    help="KV-cache blocks in the generation pool (block 0 is reserved "
         "as the null block for padded writes). Total cache memory is "
         "num_blocks * block_size * 2KV * layers * heads * head_dim * "
         "dtype bytes, allocated once at engine start; sequences "
         "allocate blocks on growth and free on retirement, and "
         "exhaustion preempts the youngest sequence "
         "(hvd_tpu_gen_preemptions_total) instead of wedging.")
GEN_MAX_SEQS = _register(
    "GEN_MAX_SEQS", 8, int,
    help="Decode batch slots: the most sequences the generation "
         "scheduler decodes concurrently (the compiled decode program's "
         "static batch dimension). The iteration-level scheduler "
         "re-forms the batch every step, so a freed slot is refilled "
         "from the waiting line within one decode step.")
GEN_PREFILL_CHUNK = _register(
    "GEN_PREFILL_CHUNK", 64, int,
    help="Prompt tokens processed per prefill call (the compiled "
         "prefill program's static chunk width). Long prompts are "
         "split into chunks and interleaved with decode steps, so a "
         "prompt of any length stalls in-flight decodes for at most "
         "one chunk per step; larger chunks prefill faster but stall "
         "decodes longer per step.")
GEN_QUEUE_DEPTH = _register(
    "GEN_QUEUE_DEPTH", 64, int,
    help="Admission control for generation: bound on submitted "
         "sequences not yet admitted to the running batch. A request "
         "arriving at a full queue is rejected immediately (HTTP 503), "
         "same policy as HVD_TPU_SERVING_QUEUE_DEPTH.")
GEN_DEADLINE_MS = _register(
    "GEN_DEADLINE_MS", 30000.0, float,
    help="Default per-TOKEN generation deadline in milliseconds "
         "(callers can set a per-request value): the allowed gap to "
         "the next emitted token, reset on every emission. A sequence "
         "that waits longer — parked at admission or preempted and "
         "awaiting blocks — fails with the serving plane's deadline "
         "error (HTTP 429). 0 disables deadlines.")
GEN_ASYNC_DEPTH = _register(
    "GEN_ASYNC_DEPTH", 1, int,
    help="Decode steps the generation scheduler enqueues ahead of the "
         "one it is waiting on (JAX async dispatch): at the default 1, "
         "step N+1 is speculatively in flight while the host consumes "
         "step N's token vector, overlapping retire/admit/stream "
         "delivery with device compute — a lane retired by step N "
         "already routed step N+1's writes to the null block on "
         "device, so speculation never corrupts the cache. 0 restores "
         "the fully synchronous loop (debugging); values above 1 are "
         "clamped to 1 (depth-1 reconciliation is what the scheduler "
         "implements).")
GEN_PREFIX_CACHE = _register(
    "GEN_PREFIX_CACHE", True, _parse_bool,
    help="Automatic prefix caching for the paged generation KV cache: "
         "full blocks are indexed by a content chain hash, retired "
         "blocks park in a cached-free LRU pool instead of being "
         "recycled, and newly admitted prompts attach the longest "
         "cached prefix with refcounts bumped so prefill starts at the "
         "first uncached token. Sharing is full-block-only (the "
         "partial tail block stays private), so cached-prefix decode "
         "is bit-identical to cold decode. Set to 0 to restore the "
         "recycle-immediately allocator.")
GEN_SPEC_MODE = _register(
    "GEN_SPEC_MODE", "off", str,
    help="Speculative decoding for the generation plane: 'off' runs "
         "the plain one-token decode loop; 'ngram' drafts by suffix-"
         "matching the sequence's own prompt + emitted tokens (zero "
         "extra model); 'draft' rolls a small draft model forward on "
         "the host (the engine's draft_model/draft_params arguments). "
         "Drafted tokens are verified in one paged forward per step "
         "and the accepted prefix is exactly what the plain decoder "
         "would have produced, so speculative output is bit-identical "
         "to non-speculative for greedy AND seeded sampling, logprobs "
         "included — the knob trades nothing but compute shape.")
GEN_SPEC_TOKENS = _register(
    "GEN_SPEC_TOKENS", 4, int,
    help="Draft width for speculative decoding: tokens proposed (and "
         "scored in one paged verify forward) per lane per step. "
         "Static — it sizes the compiled verify program's chunk "
         "(width draft+1), so changing it recompiles. Higher widths "
         "pay off only when the proposer's accept rate is high "
         "(hvd_tpu_gen_spec_accepted_total / _drafted_total); rejected "
         "draft positions are wasted compute, never cache corruption "
         "(their K/V writes are rolled back through the null block).")
GEN_BEAMS = _register(
    "GEN_BEAMS", 4, int,
    help="Maximum beam width the generation plane accepts per request "
         "(the num_beams API field; 1 = beam search disabled for the "
         "request). Static — it sizes the compiled beam step's top-k "
         "width. Beams share their common prefix KV blocks through "
         "the refcounted prefix-cache substrate and copy-on-extend "
         "only the divergent tail block; num_beams=1 output is "
         "bit-identical to plain greedy decode.")

# -- Serving fleet (no reference equivalent — serving/fleet/: the router
#    tier over N replica servers: health-aware balancing, per-tenant
#    admission, rolling hot-reload) plus the shared async HTTP front-end ------
HTTP_READ_TIMEOUT = _register(
    "HTTP_READ_TIMEOUT", 30.0, float,
    help="Per-connection socket read/write deadline (seconds) on the "
         "shared async HTTP front-end (rendezvous KV, metrics, serving, "
         "fleet router). Bounds how long a slow-loris client that starts "
         "a request and stalls can pin a worker thread, and how long a "
         "wedged client can stall a response write. 0 disables the "
         "deadline.")
FLEET_PORT = _register(
    "FLEET_PORT", 0, int,
    help="Port for the fleet router's HTTP front-end (POST /v1/infer / "
         "/v1/generate proxied to replicas, GET /healthz, POST "
         "/fleet/heartbeat/<replica>). 0 (default) binds an ephemeral "
         "port (read it back from FleetRouter.port).")
FLEET_HEARTBEAT_INTERVAL = _register(
    "FLEET_HEARTBEAT_INTERVAL", 1.0, float,
    help="Seconds between replica liveness beats to the fleet router "
         "(the serving-plane reuse of the elastic heartbeat layer). "
         "Also the router monitor's sweep interval, so ejection latency "
         "is bounded by timeout + interval.")
FLEET_HEARTBEAT_TIMEOUT = _register(
    "FLEET_HEARTBEAT_TIMEOUT", 5.0, float,
    help="Seconds of beat silence after which the router ejects an "
         "armed replica from routing (detection within 2x this bound; "
         "clamped to 2x the interval so one dropped beat never ejects). "
         "A replica whose beats resume is re-admitted automatically. "
         "0 disables heartbeat ejection (passive circuit signals still "
         "apply).")
FLEET_CIRCUIT_THRESHOLD = _register(
    "FLEET_CIRCUIT_THRESHOLD", 3, int,
    help="Consecutive connect-errors/5xx responses from one replica "
         "that open its circuit (stop routing to it). A half-open probe "
         "(GET /healthz) re-closes the circuit on success; probes back "
         "off with full jitter between HVD_TPU_FLEET_PROBE_BACKOFF and "
         "HVD_TPU_FLEET_PROBE_MAX_BACKOFF.")
FLEET_PROBE_BACKOFF = _register(
    "FLEET_PROBE_BACKOFF", 0.2, float,
    help="Initial backoff (seconds) for half-open health probes of a "
         "circuit-opened replica; doubles per failed probe with full "
         "jitter (retry.py policy) up to HVD_TPU_FLEET_PROBE_MAX_"
         "BACKOFF.")
FLEET_PROBE_MAX_BACKOFF = _register(
    "FLEET_PROBE_MAX_BACKOFF", 2.0, float,
    help="Cap (seconds) on the half-open probe backoff for circuit-"
         "opened replicas — the longest a recovered replica waits "
         "before a probe can re-admit it.")
FLEET_DRAIN_DEADLINE_SECONDS = _register(
    "FLEET_DRAIN_DEADLINE_SECONDS", 30.0, float,
    help="Rolling-reload drain deadline: the longest the rollout waits "
         "for one replica's in-flight requests to reach zero before "
         "aborting the rollout and re-admitting the replica un-swapped "
         "(fail-static: a wedged drain never takes capacity down).")
FLEET_REPLICA_CONCURRENCY = _register(
    "FLEET_REPLICA_CONCURRENCY", 8, int,
    help="Per-replica concurrent-request budget the router's admission "
         "uses to size fleet capacity (routable replicas x this). "
         "Requests beyond fleet capacity wait in the fair queue instead "
         "of piling onto replica queues.")
FLEET_TENANTS = _register(
    "FLEET_TENANTS", "", str,
    help="JSON object mapping tenant name -> {keys: [api keys], "
         "max_concurrent, max_queued, weight, priority} for the "
         "router's per-tenant admission. Omitted fields fall back to "
         "the HVD_TPU_FLEET_TENANT_CONCURRENT / _QUEUE_DEPTH / _WEIGHT "
         "defaults; unknown API keys and "
         "missing headers resolve to the built-in 'default' tenant. "
         "Empty (default) = every request is the default tenant.")
FLEET_TENANT_CONCURRENT = _register(
    "FLEET_TENANT_CONCURRENT", 4, int,
    help="Default per-tenant cap on concurrently dispatched requests "
         "(tenants can override via HVD_TPU_FLEET_TENANTS). A tenant "
         "at its cap queues; over its queue cap it gets its own 429s "
         "while other tenants keep being served.")
FLEET_TENANT_QUEUE_DEPTH = _register(
    "FLEET_TENANT_QUEUE_DEPTH", 16, int,
    help="Default per-tenant cap on requests waiting in the router's "
         "fair queue. Arrivals beyond it are rejected 429 reason="
         "quota immediately — the flooding tenant's own backpressure, "
         "not the fleet's.")
FLEET_TENANT_WEIGHT = _register(
    "FLEET_TENANT_WEIGHT", 1.0, float,
    help="Default weighted-fair-queue share per tenant (stride "
         "scheduling: a weight-2 tenant dequeues twice as often as a "
         "weight-1 tenant under contention, within a priority class). "
         "Priority classes strictly outrank weights.")
FLEET_DEFAULT_DEADLINE_MS = _register(
    "FLEET_DEFAULT_DEADLINE_MS", 0.0, float,
    help="End-to-end latency budget (ms) the fleet router mints for "
         "requests that arrive without an X-HVD-TPU-Deadline-Ms header. "
         "The budget is decremented at every hop (route -> fair-queue "
         "wait -> prefill admission -> per-token decode) and an "
         "un-meetable request is shed with HTTP 429 plus an "
         "X-HVD-TPU-Deadline-Exceeded header naming the stage that "
         "noticed. 0 (default) falls back to HVD_TPU_SERVING_DEADLINE_"
         "MS for the router's queue wait (legacy behavior).")
FLEET_HEDGE_QUANTILE = _register(
    "FLEET_HEDGE_QUANTILE", 0.0, float,
    help="Latency quantile (0..1) of the router's observed non-"
         "streaming proxy latency after which a still-pending request "
         "is hedged to a second replica: first response wins, the "
         "loser is cancelled via POST /v1/cancel. Hedges spend from "
         "the per-tenant retry budget (HVD_TPU_FLEET_RETRY_BUDGET_"
         "RATIO). 0 (default) disables hedging; the trigger arms only "
         "once enough latency samples exist to estimate the quantile.")
FLEET_RETRY_BUDGET_RATIO = _register(
    "FLEET_RETRY_BUDGET_RATIO", 0.1, float,
    help="Per-tenant token-bucket retry budget: every primary request "
         "a tenant sends earns this many retry tokens (capped at "
         "HVD_TPU_FLEET_RETRY_BUDGET_BURST) and every retry, hedge, or "
         "mid-stream failover the router issues on the tenant's behalf "
         "spends one. An exhausted budget degrades the router to "
         "pass-through — failures are relayed instead of amplified "
         "into a retry storm.")
FLEET_RETRY_BUDGET_BURST = _register(
    "FLEET_RETRY_BUDGET_BURST", 16, int,
    help="Cap (and initial fill) of the per-tenant retry-budget token "
         "bucket, in retries. Bounds how many retries/hedges/failovers "
         "the router can issue for one tenant in a burst before the "
         "HVD_TPU_FLEET_RETRY_BUDGET_RATIO accrual becomes the "
         "limiting rate.")

# -- Disaggregated prefill/decode serving (serving/disagg/: pool-split
#    fleet with content-addressed KV-block shipping) ------------------------
DISAGG_ROLE = _register(
    "DISAGG_ROLE", "colocated", str,
    help="Operating mode of this replica's generation plane: "
         "'colocated' (default) serves prefill AND decode exactly as "
         "before; 'prefill' runs chunked prefill into the paged cache, "
         "registers the prompt's full blocks in the prefix-cache index, "
         "discards the sampled token, and answers /v1/generate with a "
         "content-addressed KV manifest instead of tokens; 'decode' "
         "serves generation normally but is the fleet's target for "
         "POST /v1/kv/offer — transferred blocks register into its "
         "BlockAllocator so admission attaches them with zero "
         "full-block prefill debt. Byte-compatible: every colocated "
         "path is untouched at the default.")
DISAGG_WIRE_DTYPE = _register(
    "DISAGG_WIRE_DTYPE", "native", str,
    help="Element dtype for KV-block payloads on the /v1/kv/fetch "
         "wire: 'native' (default) ships the pool dtype bit-exactly "
         "(required for the disagg-vs-colocated bit-parity guarantee "
         "when pools are fp32); 'bf16' packs blocks through the PR 7 "
         "bfloat16 wire codec, halving transfer bytes — lossless only "
         "when the pools are already bf16.")
DISAGG_FETCH_TIMEOUT_S = _register(
    "DISAGG_FETCH_TIMEOUT_S", 5.0, float,
    help="Socket timeout (seconds) for the decode replica's "
         "POST /v1/kv/fetch pull of missing KV-block payloads from the "
         "prefill replica. On expiry (or any fetch failure — e.g. the "
         "prefill replica died mid-transfer) the offer degrades to a "
         "decode-side re-prefill: correctness is never a function of "
         "the transfer completing.")

# -- Misc -------------------------------------------------------------------
NUM_STREAMS = _register(
    "NUM_STREAMS", 1, int, alias="HOROVOD_NUM_NCCL_STREAMS",
    help="Number of round-robin dispatch lanes for fused collectives.")
BATCH_D2D_MEMCOPIES = _register(
    "BATCH_D2D_MEMCOPIES", True, _parse_bool,
    alias="HOROVOD_BATCH_D2D_MEMCOPIES")
ADASUM_MODE = _register(
    "ADASUM_MODE", "auto", str,
    help="Adasum hierarchy: auto|flat|hierarchical.")


class Config:
    """Resolves knob values: programmatic override > env(HVD_TPU_) > env(alias)
    > default. One instance lives on the global world state."""

    def __init__(self, overrides: Optional[Dict[str, Any]] = None):
        self._overrides: Dict[str, Any] = dict(overrides or {})

    def set(self, name: str, value: Any) -> None:
        if name not in _REGISTRY:
            raise KeyError(f"unknown knob {name!r}")
        self._overrides[name] = value

    def get(self, name: str) -> Any:
        return self.resolve(name)[0]

    def resolve(self, name: str) -> "tuple[Any, str]":
        """(value, source) with source one of 'override',
        'env HVD_TPU_<N>', 'env <alias>', 'scheduler <VAR>', 'default'.
        ``describe()`` prints this, so provenance can never drift from the
        actual resolution order."""
        knob = _REGISTRY[name]
        if name in self._overrides:
            return self._overrides[name], "override"
        raw = os.environ.get("HVD_TPU_" + knob.name)
        src = "env HVD_TPU_" + knob.name
        for alias in knob.aliases():
            if raw is not None:
                break
            raw = os.environ.get(alias)
            src = f"env {alias}"
        if raw is None:
            # external-scheduler fallback for the task-identity knobs
            if name in (RANK, SIZE, LOCAL_RANK, LOCAL_SIZE,
                        CROSS_RANK, CROSS_SIZE):
                ident, family = mpi_task_identity(with_source=True)
                if name in ident:
                    return ident[name], f"scheduler {family}"
            return knob.default, "default"
        try:
            return knob.parser(raw), src
        except (TypeError, ValueError):
            return knob.default, "default"

    def snapshot(self) -> Dict[str, Any]:
        return {name: self.get(name) for name in _REGISTRY}


def knobs() -> Dict[str, Knob]:
    """All registered knobs (used by the launcher to build CLI flags)."""
    return dict(_REGISTRY)


def live_config() -> "Config":
    """The initialized world's Config (programmatic overrides included),
    falling back to an env-only view — the same resolution order
    ``describe()`` reports, so a ``Config.set()`` override can never be
    silently ignored by a subsystem reading knobs outside ``init()``."""
    from . import basics
    if basics.is_initialized():
        return basics.world().config
    return Config()


def describe(cfg: Optional[Config] = None) -> str:
    """Human-readable dump of every knob's LIVE value and where it came
    from (override / env / alias env / default) — the first thing to
    check when a setting seems ignored. Uses the active world's Config
    when one exists, else a fresh env-only view."""
    if cfg is None:
        from . import basics
        w = basics.world() if basics.is_initialized() else None
        cfg = w.config if w is not None else Config()
    lines = []
    for name, knob in _REGISTRY.items():
        value, src = cfg.resolve(name)
        lines.append(f"{'HVD_TPU_' + knob.name:44s} = {value!r:24} [{src}]")
    return "\n".join(lines)
