"""Cross-replica (synchronized) batch normalization.

Reference surface: ``hvd.SyncBatchNormalization`` (TF:
/root/reference/horovod/tensorflow/sync_batch_norm.py — allreduces batch
mean and variance across ranks) and ``hvd.SyncBatchNorm`` (Torch:
/root/reference/horovod/torch/sync_batch_norm.py:199 — allgathers per-rank
sums/counts inside the autograd function). TPU-native redesign, two planes:

* **Compiled plane** (:class:`SyncBatchNorm`): a flax module whose batch
  statistics are ``lax.pmean``-reduced over the data-parallel mesh axes
  inside the jitted step — one fused XLA collective, the moral equivalent of
  the reference's allreduce-of-mean/var. Works under shard_map or pjit; with
  ``axis_name=None`` it degrades to plain BatchNorm (size-1 semantics, like
  the reference with one process).
* **Eager plane** (:func:`sync_batch_norm_stats`): computes globally-pooled
  mean/var across processes with the host-plane allreduce, for callers
  maintaining their own normalization (reference torch pattern of syncing
  running stats).

Variance is synchronized via E[x^2] - E[x]^2 of the *global* batch — the
same math the reference uses (sync_batch_norm.py: allreduce of mean and of
mean-of-squares), exact for equal per-replica batch sizes (SPMD guarantees
that on TPU).
"""

from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

import flax.linen as nn


class SyncBatchNorm(nn.Module):
    """BatchNorm whose statistics are exact over the global batch.

    Attributes mirror flax.linen.BatchNorm; ``axis_name`` is the mesh axis
    (or axes) carrying data parallelism. Use exactly like BatchNorm::

        SyncBatchNorm(axis_name="dp", use_running_average=not train)(x)
    """

    axis_name: Optional[Union[str, Sequence[str]]] = None
    use_running_average: bool = False
    momentum: float = 0.99
    epsilon: float = 1e-5
    dtype: Optional[Any] = None
    use_bias: bool = True
    use_scale: bool = True

    @nn.compact
    def __call__(self, x):
        features = x.shape[-1]
        reduce_axes = tuple(range(x.ndim - 1))
        dtype = self.dtype or x.dtype

        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros(features, jnp.float32))
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones(features, jnp.float32))

        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=reduce_axes)
            mean_sq = jnp.mean(jnp.square(xf), axis=reduce_axes)
            if self.axis_name is not None and not self.is_initializing():
                # one fused cross-replica reduction of (mean, E[x^2]) —
                # reference: allreduce of mean and var,
                # tensorflow/sync_batch_norm.py. Skipped during init(),
                # which typically runs outside shard_map (axis unbound).
                mean, mean_sq = jax.lax.pmean(
                    (mean, mean_sq), axis_name=self.axis_name)
            var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var

        y = (x.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + self.epsilon)
        if self.use_scale:
            scale = self.param("scale", nn.initializers.ones, (features,),
                               jnp.float32)
            y = y * scale
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (features,),
                              jnp.float32)
            y = y + bias
        return y.astype(dtype)


def sync_batch_norm_stats(x, process_set=None) -> Tuple[jnp.ndarray,
                                                        jnp.ndarray]:
    """Eager-plane global batch statistics: (mean, biased var) of ``x``
    pooled over all processes (reduce axes = all but last). Equal
    per-process batch sizes assumed, as in the reference's allreduce-of-
    means formulation."""
    from . import collectives as _c
    xf = jnp.asarray(x, jnp.float32)
    axes = tuple(range(xf.ndim - 1))
    local = jnp.stack([jnp.mean(xf, axis=axes),
                       jnp.mean(jnp.square(xf), axis=axes)])
    glob = _c.allreduce(local, op=_c.Average,
                        name="horovod_tpu.sync_bn.stats",
                        process_set=process_set)
    mean, mean_sq = jnp.asarray(glob)[0], jnp.asarray(glob)[1]
    return mean, jnp.maximum(mean_sq - jnp.square(mean), 0.0)
