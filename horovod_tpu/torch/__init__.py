"""PyTorch interop: train torch models with the TPU-hosted collective plane.

Reference surface: horovod/torch — ``DistributedOptimizer`` registering
per-parameter grad hooks that fire async allreduces, synchronized in
``step()`` (/root/reference/horovod/torch/optimizer.py:100-186), plus
``broadcast_parameters``/``broadcast_optimizer_state``
(torch/functions.py). Here the collectives are horovod_tpu's eager plane
(XLA over ICI/DCN); torch tensors bridge through **DLPack** — zero-copy on
CPU-resident tensors (the analogue of the reference's adapter layer,
torch/mpi_ops_v2.cc + adapter_v2.cc) — with a numpy copy as the fallback for
layouts DLPack can't express. Async ops return handles whose staging and
dispatch run on the collective dispatcher thread, so the autograd engine's
backward pass overlaps communication (reference: gpu_operations.cc:60-87
finalizer pipelining).

Usage (identical shape to the reference's 5-line recipe)::

    import horovod_tpu.torch as hvd
    hvd.init()
    optimizer = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1 * hvd.size()),
        named_parameters=model.named_parameters())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
"""

import threading
from typing import Any, Dict, Iterator, Optional, Tuple

import numpy as np

from .. import basics as _basics
from .. import collectives as _c
from ..basics import (  # noqa: F401  (reference API parity re-exports)
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size,
)
from ..collectives import (  # noqa: F401
    Average, Sum, Adasum, poll, join, join_round,
)
from ..compression import Compression  # noqa: F401


def _to_numpy(t) -> np.ndarray:
    """torch tensor -> numpy, zero-copy via DLPack whenever the memory is
    CPU-resident and expressible (bfloat16 crosses via a bit-pattern view
    into ml_dtypes.bfloat16, still zero-copy)."""
    import torch
    t = t.detach()
    if t.device.type != "cpu":
        t = t.cpu()
    if t.dtype == torch.bfloat16:
        import ml_dtypes
        return (t.contiguous().view(torch.uint16).numpy()
                .view(ml_dtypes.bfloat16))
    try:
        return np.from_dlpack(t)
    except Exception:
        return t.numpy() if t.is_contiguous() else t.contiguous().numpy()


def _from_numpy(a, dtype):
    """jax/numpy result -> torch tensor of the requested dtype. DLPack
    import (zero-copy for CPU-backed jax arrays) with a numpy-copy fallback;
    the result buffer is exclusively ours once the handle is finished, so the
    shared view is safe to hand out."""
    import torch
    try:
        t = torch.from_dlpack(a)
    except Exception:
        arr = np.asarray(a)
        if arr.dtype.name == "bfloat16":
            t = torch.from_numpy(
                arr.view(np.uint16).copy()).view(torch.bfloat16)
        else:
            t = torch.from_numpy(np.array(arr))
    return t.to(dtype) if t.dtype != dtype else t


# -- differentiable collectives (reference: the autograd Functions of
#    torch/mpi_ops.py:144-157, 290-308, 375-389 — allreduce's gradient is
#    the same allreduce of the upstream gradient; allgather's is a
#    sum-allreduce narrowed to this process's rows; broadcast's is a
#    sum-allreduce delivered to the root and zero elsewhere). Built
#    lazily so importing this module never requires torch. ---------------

_autograd_cache: Dict[str, Any] = {}


def _autograd_fns():
    fns = _autograd_cache.get("fns")
    if fns is not None:
        return fns
    import torch

    class _AllreduceFn(torch.autograd.Function):
        @staticmethod
        def forward(ctx, tensor, op, prescale, postscale, name,
                    compression):
            ctx.op, ctx.pre, ctx.post = op, prescale, postscale
            ctx.compression = compression
            compressed, cc = compression.compress(_to_numpy(tensor))
            out = _c.allreduce(compressed, op=op, name=name,
                               prescale_factor=prescale,
                               postscale_factor=postscale)
            return _from_numpy(compression.decompress(out, cc),
                               tensor.dtype)

        @staticmethod
        def backward(ctx, grad):
            # compression is wire-level (numpy boundary), so the backward
            # pass compresses its traffic too and gradients still flow
            compressed, cc = ctx.compression.compress(_to_numpy(grad))
            out = _c.allreduce(compressed, op=ctx.op,
                               prescale_factor=ctx.pre,
                               postscale_factor=ctx.post)
            return (_from_numpy(ctx.compression.decompress(out, cc),
                                grad.dtype),
                    None, None, None, None, None)

    from ..functions import allgather_grad_numpy, broadcast_grad_numpy

    class _AllgatherFn(torch.autograd.Function):
        @staticmethod
        def forward(ctx, tensor, name):
            ctx.was_scalar = tensor.ndim == 0
            ctx.dim0 = int(tensor.shape[0]) if tensor.ndim else 1
            out = _c.allgather(_to_numpy(tensor), name=name)
            return _from_numpy(out, tensor.dtype)

        @staticmethod
        def backward(ctx, grad):
            piece = allgather_grad_numpy(_to_numpy(grad), ctx.dim0,
                                         ctx.was_scalar)
            return _from_numpy(piece, grad.dtype), None

    class _BroadcastFn(torch.autograd.Function):
        @staticmethod
        def forward(ctx, tensor, root_rank, name):
            ctx.root_rank = root_rank
            out = _c.broadcast(_to_numpy(tensor), root_rank=root_rank,
                               name=name)
            return _from_numpy(out, tensor.dtype)

        @staticmethod
        def backward(ctx, grad):
            return (_from_numpy(
                broadcast_grad_numpy(_to_numpy(grad), ctx.root_rank),
                grad.dtype), None, None)

    fns = {"allreduce": _AllreduceFn, "allgather": _AllgatherFn,
           "broadcast": _BroadcastFn}
    _autograd_cache["fns"] = fns
    return fns


def allreduce(tensor, average=None, name: Optional[str] = None, op=None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              compression=Compression.none):
    """Synchronous allreduce of a torch tensor; returns a torch tensor
    (reference: torch/mpi_ops.py:158-224). Differentiable: when
    ``tensor.requires_grad``, gradients flow via an allreduce of the
    upstream gradient; compression is wire-level (applied at the numpy
    boundary inside the autograd Function, forward AND backward), so it
    never detaches the graph."""
    if getattr(tensor, "requires_grad", False):
        op_r = _c._resolve_op(average, op)
        return _autograd_fns()["allreduce"].apply(
            tensor, op_r, prescale_factor, postscale_factor, name,
            compression)
    compressed, cctx = compression.compress(_to_numpy(tensor))
    out = _c.allreduce(compressed, average=average, name=name, op=op,
                       prescale_factor=prescale_factor,
                       postscale_factor=postscale_factor)
    out = compression.decompress(out, cctx)
    return _from_numpy(out, tensor.dtype)


def allreduce_(tensor, average=None, name: Optional[str] = None, op=None,
               prescale_factor: float = 1.0, postscale_factor: float = 1.0):
    """In-place allreduce: ``tensor`` holds the reduced value on return
    (reference: torch/mpi_ops.py:225-253 allreduce_)."""
    return synchronize(allreduce_async_(
        tensor, average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor))


def allgather(tensor, name: Optional[str] = None):
    """Concatenate along dim 0 across processes; differentiable like the
    reference (torch/mpi_ops.py:290-336)."""
    if getattr(tensor, "requires_grad", False):
        return _autograd_fns()["allgather"].apply(tensor, name)
    out = _c.allgather(_to_numpy(tensor), name=name)
    return _from_numpy(out, tensor.dtype)


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    """Broadcast from ``root_rank``; differentiable like the reference
    (torch/mpi_ops.py:375-439)."""
    if getattr(tensor, "requires_grad", False):
        return _autograd_fns()["broadcast"].apply(tensor, root_rank, name)
    out = _c.broadcast(_to_numpy(tensor), root_rank=root_rank, name=name)
    return _from_numpy(out, tensor.dtype)


def broadcast_(tensor, root_rank: int, name: Optional[str] = None):
    """In-place broadcast (reference: torch/mpi_ops.py:440-462)."""
    return synchronize(broadcast_async_(tensor, root_rank, name=name))


def alltoall(tensor, splits=None, name: Optional[str] = None):
    out = _c.alltoall(_to_numpy(tensor), splits=splits, name=name)
    return _from_numpy(out, tensor.dtype)


# -- async handle API (reference: torch/mpi_ops.py:463-517) ------------------

_handle_meta: Dict[int, Any] = {}
_handle_meta_lock = threading.Lock()
_HANDLE_META_CAP = 4096


def _remember_handle(h: int, dtype, target=None) -> int:
    """Track a handle's torch dtype (and, for the in-place ``*_``
    variants, the tensor to copy the result into at synchronize time),
    reclaiming abandoned handles.

    A caller that polls a handle and never synchronizes it would otherwise
    grow this map (and the collective table) forever; past the cap, the
    oldest done-but-unconsumed handles are released. The in-place target
    is held STRONGLY until synchronize/eviction — callers routinely pass
    temporary wrappers over live storage (``p.grad.data``), and a weak
    reference would silently drop the in-place write when the wrapper is
    collected (the reference's HandleManager likewise holds the output
    tensor until synchronize). The cost: an abandoned in-place handle
    pins its tensor until evicted."""
    with _handle_meta_lock:
        _handle_meta[h] = (dtype, target)
        if len(_handle_meta) > _HANDLE_META_CAP:
            for old in list(_handle_meta):   # insertion order = oldest first
                if old == h or len(_handle_meta) <= _HANDLE_META_CAP // 2:
                    break
                try:
                    done = _c.poll(old)
                except Exception:
                    # already synchronized through the raw API; meta is stale
                    _handle_meta.pop(old, None)
                    continue
                if done:
                    try:
                        _c.release(old)
                    except Exception:
                        pass
                    _handle_meta.pop(old, None)
    return h


def allreduce_async(tensor, average=None, name: Optional[str] = None,
                    op=None, prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0) -> int:
    h = _c.allreduce_async(_to_numpy(tensor), average=average, name=name,
                           op=op, prescale_factor=prescale_factor,
                           postscale_factor=postscale_factor)
    return _remember_handle(h, tensor.dtype)


def allreduce_async_(tensor, average=None, name: Optional[str] = None,
                     op=None, prescale_factor: float = 1.0,
                     postscale_factor: float = 1.0) -> int:
    """Async in-place allreduce: ``synchronize(handle)`` writes the
    reduced value into ``tensor`` and returns it (reference:
    torch/mpi_ops.py allreduce_async_). Do not mutate ``tensor`` between
    submission and synchronize — the staging may read it lazily."""
    h = _c.allreduce_async(_to_numpy(tensor), average=average, name=name,
                           op=op, prescale_factor=prescale_factor,
                           postscale_factor=postscale_factor)
    return _remember_handle(h, tensor.dtype, target=tensor)


def broadcast_async_(tensor, root_rank: int,
                     name: Optional[str] = None) -> int:
    """Async in-place broadcast (reference: torch/mpi_ops.py
    broadcast_async_)."""
    h = _c.broadcast_async(_to_numpy(tensor), root_rank=root_rank,
                           name=name)
    return _remember_handle(h, tensor.dtype, target=tensor)


def allgather_async(tensor, name: Optional[str] = None) -> int:
    h = _c.allgather_async(_to_numpy(tensor), name=name)
    return _remember_handle(h, tensor.dtype)


def broadcast_async(tensor, root_rank: int, name: Optional[str] = None) -> int:
    h = _c.broadcast_async(_to_numpy(tensor), root_rank=root_rank, name=name)
    return _remember_handle(h, tensor.dtype)


def alltoall_async(tensor, splits=None, name: Optional[str] = None) -> int:
    h = _c.alltoall_async(_to_numpy(tensor), splits=splits, name=name)
    return _remember_handle(h, tensor.dtype)


def synchronize(handle: int):
    """Wait for an async op; returns the result as a torch tensor when the
    handle was created through this module, else the raw array. Handles
    from the in-place ``*_`` variants copy the result into the original
    tensor and return it (reference HandleManager in-place semantics)."""
    with _handle_meta_lock:
        meta = _handle_meta.pop(handle, None)
    out = _c.synchronize(handle)
    if meta is None:
        return out
    dtype, target = meta
    result = _from_numpy(out, dtype)
    if target is not None:
        import torch
        with torch.no_grad():
            target.copy_(result)
        return target
    return result


_synchronize_handle = _c.synchronize


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """In-place broadcast of a ``state_dict()`` or ``named_parameters``
    iterable (reference: torch/functions.py broadcast_parameters)."""
    import torch
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = sorted(dict(params).items())
    for name, p in items:
        if not isinstance(p, torch.Tensor):
            continue
        out = _c.broadcast(_to_numpy(p), root_rank=root_rank,
                           name=f"bcast.param.{name}")
        with torch.no_grad():
            p.copy_(_from_numpy(out, p.dtype))


def broadcast_optimizer_state(optimizer, root_rank: int = 0) -> None:
    """Broadcast optimizer state tensors and scalar hyperparameters from
    root (reference: torch/functions.py broadcast_optimizer_state).

    The walk is driven by ROOT's state structure, broadcast first as a
    spec: a freshly-constructed worker with empty state still issues the
    identical collective sequence (contributing zeros that root's values
    overwrite), matching the reference's design of rebuilding state from
    root's pickled metadata."""
    import torch
    from ..functions import broadcast_object
    local_state = optimizer.state_dict()
    is_root = _basics.rank() == root_rank

    spec: Dict[str, Any] = {"meta": None, "entries": []}
    if is_root:
        spec["meta"] = {k: v for k, v in local_state.items() if k != "state"}
        for pid, pstate in sorted(local_state.get("state", {}).items()):
            for key, val in sorted(pstate.items()):
                if isinstance(val, torch.Tensor):
                    spec["entries"].append(
                        ("t", pid, key, tuple(val.shape), str(val.dtype)))
                else:
                    spec["entries"].append(("o", pid, key, val))
    spec = broadcast_object(spec, root_rank=root_rank, name="bcast.opt.spec")

    new_state: Dict[Any, Dict[str, Any]] = {}
    for entry in spec["entries"]:
        if entry[0] == "t":
            _, pid, key, shape, dtype_s = entry
            dtype = getattr(torch, dtype_s.split(".")[-1])
            local = local_state.get("state", {}).get(pid, {}).get(key)
            if isinstance(local, torch.Tensor) \
                    and tuple(local.shape) == shape:
                contrib = local.to(dtype)
            else:
                contrib = torch.zeros(shape, dtype=dtype)
            out = _c.broadcast(_to_numpy(contrib), root_rank=root_rank,
                               name=f"bcast.opt.{pid}.{key}")
            new_state.setdefault(pid, {})[key] = _from_numpy(out, dtype)
        else:
            _, pid, key, val = entry
            new_state.setdefault(pid, {})[key] = val
    optimizer.load_state_dict({**spec["meta"], "state": new_state})


class _DistributedOptimizer:
    """Wraps a torch optimizer: backward hooks collect ready gradients into
    fixed fusion buckets; each full bucket fires ONE grouped async
    allreduce; ``step()`` synchronizes and applies.

    Reference: torch/optimizer.py:100-186 (per-parameter hooks) fused
    through the fusion buffer (collective_operations.cc:37-81). Here the
    fusion is at *dispatch granularity*: a ResNet-scale model issues
    ~total_grad_bytes/threshold grouped dispatches per step instead of one
    per parameter. Buckets are planned once, from reverse parameter
    registration order (later layers' gradients materialize first in
    backward — torch DDP's bucketing heuristic), so every process forms
    identical buckets without negotiation; a bucket fires as soon as all
    its members' gradients have accumulated, preserving comm/compute
    overlap."""

    def __init__(self, optimizer, named_parameters=None, op=_c.Average,
                 backward_passes_per_step: int = 1,
                 compression=Compression.none,
                 gradient_predivide_factor: float = 1.0,
                 fusion_threshold_bytes: Optional[int] = None):
        if gradient_predivide_factor != 1.0 and op != _c.Average:
            raise ValueError(
                "gradient_predivide_factor only applies to op=Average "
                "(reference: torch/optimizer.py:395-398)")
        self._opt = optimizer
        self._op = op
        self._bpps = backward_passes_per_step
        self._compression = compression
        # Reference-parity knob (torch/__init__.py DistributedOptimizer):
        # there the factor splits the averaging divide around the fp16
        # summation to control overflow. Here the XLA plane folds
        # prescale*postscale into one scalar and accumulates half dtypes
        # in fp32 regardless (_combined_scale/_allreduce_impl), so the
        # factor is accepted for API parity and is numerically neutral —
        # the overflow problem it works around does not exist on this
        # data plane.
        self._prescale = 1.0 / gradient_predivide_factor
        self._postscale = gradient_predivide_factor
        self._fusion_threshold = fusion_threshold_bytes
        self._pass_count: Dict[int, int] = {}
        self._ctxs: Dict[Any, Any] = {}
        self._names: Dict[Any, str] = {}
        all_params = [p for group in optimizer.param_groups
                      for p in group["params"]]
        if named_parameters is not None:
            named = list(named_parameters)
            # every optimizer parameter must be named, or its gradients
            # would silently skip synchronization (reference:
            # torch/optimizer.py:57-62 raises for unnamed parameters)
            named_ids = {id(p) for _, p in named}
            missing = [p for p in all_params if id(p) not in named_ids]
            if missing:
                raise ValueError(
                    "named_parameters was specified, but one or more model "
                    "parameters were not named. Python object ids: " +
                    ", ".join(str(id(p)) for p in missing))
        else:
            named = [(f"param.{gi}.{pi}", p)
                     for gi, group in enumerate(optimizer.param_groups)
                     for pi, p in enumerate(group["params"])]
        seen = set()
        hooked = []
        for name, p in named:
            if name in seen:
                raise ValueError(
                    f"duplicate parameter name {name!r} (reference "
                    f"semantics: optimizer.py name dedup)")
            seen.add(name)
            if p.requires_grad:
                self._names[p] = name
                hooked.append(p)
                p.register_post_accumulate_grad_hook(self._make_hook())
        self._plan_buckets(hooked)

    @staticmethod
    def _np_sizing_dtype(p):
        """numpy dtype of equal itemsize, for bucket size planning only."""
        s = str(p.dtype).replace("torch.", "")
        try:
            return np.dtype(s)
        except TypeError:   # bfloat16 & friends: width is what matters
            return np.dtype(np.uint16) if "16" in s else np.dtype(np.float32)

    def _plan_buckets(self, params) -> None:
        from ..fusion import plan_buckets
        ordered = list(reversed(params))   # approximate readiness order
        buckets = plan_buckets(
            [(tuple(p.shape), self._np_sizing_dtype(p)) for p in ordered],
            self._threshold())
        self._bucket_members = [[ordered[i] for i in b] for b in buckets]
        self._bucket_of: Dict[int, int] = {
            id(p): bi for bi, b in enumerate(self._bucket_members)
            for p in b}
        # per-step mutable state
        self._bucket_ready: Dict[int, Dict[int, Any]] = {}
        self._group_handles: list = []
        self._fired_ids: set = set()   # ids staged into a fired bucket
        self._should_sync = True

    def _threshold(self) -> int:
        if self._fusion_threshold is not None:
            return int(self._fusion_threshold)
        try:
            from .. import config as _config
            return int(_basics.world().config.get(_config.FUSION_THRESHOLD))
        except Exception:
            return 64 * 1024 * 1024

    # hooks ------------------------------------------------------------------
    def _stage_payload(self, p) -> np.ndarray:
        """What this parameter contributes to its bucket's collective: the
        (possibly accumulated) gradient. The Adasum delta subclass stages
        the local optimizer-step delta instead."""
        grad = _to_numpy(p.grad)
        if self._bpps > 1:
            grad = grad / self._bpps
        return grad

    def _make_hook(self):
        def hook(p):
            n = self._pass_count.get(id(p), 0) + 1
            self._pass_count[id(p)] = n
            if n >= self._bpps:
                bid = self._bucket_of[id(p)]
                ready = self._bucket_ready.setdefault(bid, {})
                # O(1) duplicate-fire guard (fired-bucket membership is
                # tracked as a set of ids, not rescanned per hook)
                if id(p) in ready or id(p) in self._fired_ids:
                    raise AssertionError(
                        "Gradients were computed more than "
                        "backward_passes_per_step times before call to "
                        "step(). Increase backward_passes_per_step to "
                        "accumulate gradients locally (reference: "
                        "torch/optimizer.py:122-126).")
                self._pass_count[id(p)] = 0
                # compress on the wire (reference: torch/optimizer.py:111-117
                # compression hook); decompressed in synchronize()
                compressed, ctx = self._compression.compress(
                    self._stage_payload(p))
                self._ctxs[p] = ctx
                ready[id(p)] = compressed
                if len(ready) == len(self._bucket_members[bid]):
                    self._fire_bucket(bid)
        return hook

    def _fire_bucket(self, bid: int) -> None:
        import zlib
        ready = self._bucket_ready.pop(bid, None)
        if not ready:
            return
        members = [p for p in self._bucket_members[bid] if id(p) in ready]
        vals = [ready[id(p)] for p in members]
        # Stable name across steps (no step counter): the consistency
        # check's response cache then validates each bucket once, not once
        # per step. The MEMBER-NAME digest makes membership part of the
        # collective identity: same-shaped parameters missing on different
        # processes would otherwise fingerprint identically and silently
        # reduce mismatched gradients together; with the digest the names
        # differ and the consistency exchange fails loudly instead.
        digest = zlib.crc32("|".join(
            self._names[p] for p in members).encode()) & 0xFFFFFFFF
        h = _c.grouped_allreduce_async(
            vals, op=self._op,
            prescale_factor=self._prescale,
            postscale_factor=self._postscale,
            name=f"grad.bucket.{bid}."
                 f"{len(members)}of{len(self._bucket_members[bid])}"
                 f".{digest:08x}")
        self._group_handles.append((h, members))
        self._fired_ids.update(id(p) for p in members)

    # torch optimizer protocol ----------------------------------------------
    def _apply_result(self, p, out) -> None:
        """Land a reduced bucket member: the base optimizer overwrites the
        gradient; the Adasum delta subclass advances the parameter."""
        import torch
        with torch.no_grad():
            p.grad.copy_(_from_numpy(out, p.grad.dtype))

    def _flush_and_drain(self):
        # Flush partially-ready buckets (params whose peers produced no
        # gradient this step, e.g. frozen or unused branches). The partial
        # count is part of the collective name, so processes diverging in
        # WHICH grads exist fail the consistency check loudly rather than
        # mispairing buckets.
        for bid in sorted(self._bucket_ready):
            self._fire_bucket(bid)
        if _basics.size() > 1:
            # round marker for cooperative Join (uneven data): joined ranks
            # pair this with their replay loop (collectives.join_round)
            _c.join_round()
        for h, members in self._group_handles:
            outs = _synchronize_handle(h)
            for p, out in zip(members, outs):
                out = self._compression.decompress(
                    out, self._ctxs.pop(p, None))
                self._apply_result(p, out)
        self._group_handles = []
        self._bucket_ready = {}
        self._fired_ids = set()

    def synchronize(self):
        self._flush_and_drain()

    def skip_synchronize(self):
        """Context manager: make the next ``step()`` skip its implicit
        ``synchronize()`` — for callers that synchronized manually to
        modify gradients in place (reference: torch/optimizer.py
        skip_synchronize + gradient-clipping recipe)."""
        import contextlib

        @contextlib.contextmanager
        def ctx():
            self._should_sync = False
            try:
                yield
            finally:
                self._should_sync = True
        return ctx()

    def step(self, closure=None):
        if self._should_sync:
            self.synchronize()
        return self._opt.step(closure)

    def zero_grad(self, *a, **kw):
        return self._opt.zero_grad(*a, **kw)

    def state_dict(self, *a, **kw):
        return self._opt.state_dict(*a, **kw)

    def load_state_dict(self, *a, **kw):
        return self._opt.load_state_dict(*a, **kw)

    @property
    def param_groups(self):
        return self._opt.param_groups

    @property
    def state(self):
        return self._opt.state

    def __getattr__(self, item):
        return getattr(self._opt, item)


class _DistributedAdasumDeltaOptimizer(_DistributedOptimizer):
    """Adasum on optimizer DELTAS rather than gradients (reference
    behavior: _DistributedAdasumOptimizer, torch/optimizer.py:196-364;
    pairwise rule adasum.h:385-396): each worker steps its wrapped
    optimizer locally against its own gradient, the resulting parameter
    delta (``-lr*f(g)``) is Adasum-combined across workers, and the
    parameters advance by the combined delta — the scale-invariant rule
    then automatically balances workers whose learning rates or gradient
    magnitudes differ.

    TPU-shaped implementation: shares the base class's bucket planning and
    membership-digest naming, but stages deltas (computed by restricting
    the inner optimizer's ``param_groups`` to the one ready parameter and
    stepping it) and applies the combined delta to ``p.data`` in
    ``step()``; the inner optimizer has already consumed the gradient.
    """

    def __init__(self, optimizer, named_parameters=None,
                 backward_passes_per_step: int = 1,
                 compression=Compression.none,
                 fusion_threshold_bytes: Optional[int] = None):
        super().__init__(
            optimizer, named_parameters=named_parameters, op=_c.Adasum,
            backward_passes_per_step=backward_passes_per_step,
            compression=compression,
            fusion_threshold_bytes=fusion_threshold_bytes)
        self._start: Dict[int, Any] = {}   # id(p) -> pre-step scratch copy

    def _stage_payload(self, p) -> np.ndarray:
        return _to_numpy(self._local_delta(p))

    def _local_delta(self, p):
        """-lr*f(g) for this parameter: snapshot, step the inner optimizer
        on p alone, measure the movement, and roll p back (parameters only
        advance in ``step()``, by the globally combined delta)."""
        import torch
        with torch.no_grad():
            start = self._start.get(id(p))
            if start is None:
                start = self._start[id(p)] = torch.empty_like(p.data)
            start.copy_(p.data)
        stash = []
        for g in self._opt.param_groups:
            stash.append(g["params"])
            g["params"] = [q for q in g["params"] if q is p]
        try:
            self._opt.step()
        finally:
            for s, g in zip(stash, self._opt.param_groups):
                g["params"] = s
        with torch.no_grad():
            delta = p.data - start
            p.data.copy_(start)
        return delta

    def synchronize(self):
        # Deltas can only be applied together with the parameter advance in
        # step(); a standalone synchronize has nothing meaningful to expose
        # (reference: _DistributedAdasumOptimizer.synchronize is a no-op).
        pass

    def skip_synchronize(self):
        raise AssertionError(
            "skip_synchronize is not supported with the Adasum delta "
            "optimizer: deltas are reduced and applied inside step().")

    def _apply_result(self, p, out) -> None:
        import torch
        with torch.no_grad():
            p.data.add_(_from_numpy(out, p.dtype))

    def step(self, closure=None):
        loss = closure() if closure is not None else None
        # Parameters whose hooks never fired this step (e.g. an unused
        # branch still carrying a stale gradient) contribute their delta
        # now so every process issues identical collectives (reference:
        # step()'s missing_p path).
        staged = {pid for ready in self._bucket_ready.values()
                  for pid in ready}
        for p in self._names:
            if id(p) in self._fired_ids or id(p) in staged:
                continue
            if p.grad is None:
                continue
            bid = self._bucket_of[id(p)]
            ready = self._bucket_ready.setdefault(bid, {})
            compressed, ctx = self._compression.compress(
                self._stage_payload(p))
            self._ctxs[p] = ctx
            ready[id(p)] = compressed
            # Reset accumulation like the hook path does (reference:
            # step() resets _allreduce_delay for every handled param,
            # optimizer.py:355) — otherwise with bpps>1 a partially
            # accumulated param fires early next step.
            self._pass_count[id(p)] = 0
        self._flush_and_drain()
        return loss

    def zero_grad(self, *a, **kw):
        if self._group_handles or any(self._bucket_ready.values()):
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() "
                "but before optimizer.step(); with the Adasum delta "
                "optimizer this races with the in-flight delta reduction "
                "(reference: torch/optimizer.py zero_grad guard).")
        return self._opt.zero_grad(*a, **kw)


def DistributedOptimizer(optimizer, named_parameters=None, op=_c.Average,
                         backward_passes_per_step: int = 1,
                         compression=Compression.none,
                         gradient_predivide_factor: float = 1.0,
                         fusion_threshold_bytes: Optional[int] = None):
    if op == _c.Adasum and _basics.size() > 1:
        # Reference dispatch (torch/optimizer.py:412-420): op=Adasum with a
        # multi-process world means the DELTA optimizer; a single process
        # keeps the plain gradient path (Adasum of one tensor = identity).
        if gradient_predivide_factor != 1.0:
            raise ValueError(
                "gradient_predivide_factor only applies to op=Average "
                "(reference: torch/optimizer.py:395-398)")
        return _DistributedAdasumDeltaOptimizer(
            optimizer, named_parameters=named_parameters,
            backward_passes_per_step=backward_passes_per_step,
            compression=compression,
            fusion_threshold_bytes=fusion_threshold_bytes)
    return _DistributedOptimizer(
        optimizer, named_parameters=named_parameters, op=op,
        backward_passes_per_step=backward_passes_per_step,
        compression=compression,
        gradient_predivide_factor=gradient_predivide_factor,
        fusion_threshold_bytes=fusion_threshold_bytes)


def __getattr__(name):  # PEP 562 lazy exports (torch import stays deferred)
    if name == "SyncBatchNorm":
        from .sync_batch_norm import get_sync_batch_norm_class
        return get_sync_batch_norm_class()
    if name == "elastic":
        import importlib
        return importlib.import_module(".elastic", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
