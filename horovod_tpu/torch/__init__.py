"""PyTorch interop: train torch models with the TPU-hosted collective plane.

Reference surface: horovod/torch — ``DistributedOptimizer`` registering
per-parameter grad hooks that fire async allreduces, synchronized in
``step()`` (/root/reference/horovod/torch/optimizer.py:100-186), plus
``broadcast_parameters``/``broadcast_optimizer_state``
(torch/functions.py). Here the collectives are horovod_tpu's eager plane
(XLA over ICI/DCN); torch tensors bridge through **DLPack** — zero-copy on
CPU-resident tensors (the analogue of the reference's adapter layer,
torch/mpi_ops_v2.cc + adapter_v2.cc) — with a numpy copy as the fallback for
layouts DLPack can't express. Async ops return handles whose staging and
dispatch run on the collective dispatcher thread, so the autograd engine's
backward pass overlaps communication (reference: gpu_operations.cc:60-87
finalizer pipelining).

Usage (identical shape to the reference's 5-line recipe)::

    import horovod_tpu.torch as hvd
    hvd.init()
    optimizer = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1 * hvd.size()),
        named_parameters=model.named_parameters())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
"""

import threading
from typing import Any, Dict, Iterator, Optional, Tuple

import numpy as np

from .. import basics as _basics
from .. import collectives as _c
from ..basics import (  # noqa: F401  (reference API parity re-exports)
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size,
)
from ..collectives import (  # noqa: F401
    Average, Sum, Adasum, poll, join, join_round,
)
from ..compression import Compression  # noqa: F401


def _to_numpy(t) -> np.ndarray:
    """torch tensor -> numpy, zero-copy via DLPack whenever the memory is
    CPU-resident and expressible (bfloat16 crosses via a bit-pattern view
    into ml_dtypes.bfloat16, still zero-copy)."""
    import torch
    t = t.detach()
    if t.device.type != "cpu":
        t = t.cpu()
    if t.dtype == torch.bfloat16:
        import ml_dtypes
        return (t.contiguous().view(torch.uint16).numpy()
                .view(ml_dtypes.bfloat16))
    try:
        return np.from_dlpack(t)
    except Exception:
        return t.numpy() if t.is_contiguous() else t.contiguous().numpy()


def _from_numpy(a, dtype):
    """jax/numpy result -> torch tensor of the requested dtype. DLPack
    import (zero-copy for CPU-backed jax arrays) with a numpy-copy fallback;
    the result buffer is exclusively ours once the handle is finished, so the
    shared view is safe to hand out."""
    import torch
    try:
        t = torch.from_dlpack(a)
    except Exception:
        arr = np.asarray(a)
        if arr.dtype.name == "bfloat16":
            t = torch.from_numpy(
                arr.view(np.uint16).copy()).view(torch.bfloat16)
        else:
            t = torch.from_numpy(np.array(arr))
    return t.to(dtype) if t.dtype != dtype else t


def allreduce(tensor, average=None, name: Optional[str] = None, op=None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0):
    """Synchronous allreduce of a torch tensor; returns a torch tensor
    (reference: torch/mpi_ops.py:158-200)."""
    out = _c.allreduce(_to_numpy(tensor), average=average, name=name, op=op,
                       prescale_factor=prescale_factor,
                       postscale_factor=postscale_factor)
    return _from_numpy(out, tensor.dtype)


def allgather(tensor, name: Optional[str] = None):
    out = _c.allgather(_to_numpy(tensor), name=name)
    return _from_numpy(out, tensor.dtype)


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    out = _c.broadcast(_to_numpy(tensor), root_rank=root_rank, name=name)
    return _from_numpy(out, tensor.dtype)


def alltoall(tensor, splits=None, name: Optional[str] = None):
    out = _c.alltoall(_to_numpy(tensor), splits=splits, name=name)
    return _from_numpy(out, tensor.dtype)


# -- async handle API (reference: torch/mpi_ops.py:463-517) ------------------

_handle_meta: Dict[int, Any] = {}
_handle_meta_lock = threading.Lock()


def allreduce_async(tensor, average=None, name: Optional[str] = None,
                    op=None, prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0) -> int:
    h = _c.allreduce_async(_to_numpy(tensor), average=average, name=name,
                           op=op, prescale_factor=prescale_factor,
                           postscale_factor=postscale_factor)
    with _handle_meta_lock:
        _handle_meta[h] = tensor.dtype
    return h


def allgather_async(tensor, name: Optional[str] = None) -> int:
    h = _c.allgather_async(_to_numpy(tensor), name=name)
    with _handle_meta_lock:
        _handle_meta[h] = tensor.dtype
    return h


def broadcast_async(tensor, root_rank: int, name: Optional[str] = None) -> int:
    h = _c.broadcast_async(_to_numpy(tensor), root_rank=root_rank, name=name)
    with _handle_meta_lock:
        _handle_meta[h] = tensor.dtype
    return h


def synchronize(handle: int):
    """Wait for an async op; returns the result as a torch tensor when the
    handle was created through this module, else the raw array."""
    with _handle_meta_lock:
        dtype = _handle_meta.pop(handle, None)
    out = _c.synchronize(handle)
    return _from_numpy(out, dtype) if dtype is not None else out


_synchronize_handle = _c.synchronize


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """In-place broadcast of a ``state_dict()`` or ``named_parameters``
    iterable (reference: torch/functions.py broadcast_parameters)."""
    import torch
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = sorted(dict(params).items())
    for name, p in items:
        if not isinstance(p, torch.Tensor):
            continue
        out = _c.broadcast(_to_numpy(p), root_rank=root_rank,
                           name=f"bcast.param.{name}")
        with torch.no_grad():
            p.copy_(_from_numpy(out, p.dtype))


def broadcast_optimizer_state(optimizer, root_rank: int = 0) -> None:
    """Broadcast optimizer state tensors and scalar hyperparameters from
    root (reference: torch/functions.py broadcast_optimizer_state).

    The walk is driven by ROOT's state structure, broadcast first as a
    spec: a freshly-constructed worker with empty state still issues the
    identical collective sequence (contributing zeros that root's values
    overwrite), matching the reference's design of rebuilding state from
    root's pickled metadata."""
    import torch
    from ..functions import broadcast_object
    local_state = optimizer.state_dict()
    is_root = _basics.rank() == root_rank

    spec: Dict[str, Any] = {"meta": None, "entries": []}
    if is_root:
        spec["meta"] = {k: v for k, v in local_state.items() if k != "state"}
        for pid, pstate in sorted(local_state.get("state", {}).items()):
            for key, val in sorted(pstate.items()):
                if isinstance(val, torch.Tensor):
                    spec["entries"].append(
                        ("t", pid, key, tuple(val.shape), str(val.dtype)))
                else:
                    spec["entries"].append(("o", pid, key, val))
    spec = broadcast_object(spec, root_rank=root_rank, name="bcast.opt.spec")

    new_state: Dict[Any, Dict[str, Any]] = {}
    for entry in spec["entries"]:
        if entry[0] == "t":
            _, pid, key, shape, dtype_s = entry
            dtype = getattr(torch, dtype_s.split(".")[-1])
            local = local_state.get("state", {}).get(pid, {}).get(key)
            if isinstance(local, torch.Tensor) \
                    and tuple(local.shape) == shape:
                contrib = local.to(dtype)
            else:
                contrib = torch.zeros(shape, dtype=dtype)
            out = _c.broadcast(_to_numpy(contrib), root_rank=root_rank,
                               name=f"bcast.opt.{pid}.{key}")
            new_state.setdefault(pid, {})[key] = _from_numpy(out, dtype)
        else:
            _, pid, key, val = entry
            new_state.setdefault(pid, {})[key] = val
    optimizer.load_state_dict({**spec["meta"], "state": new_state})


class _DistributedOptimizer:
    """Wraps a torch optimizer: backward hooks fire async allreduces per
    parameter; ``step()`` synchronizes and applies (reference:
    torch/optimizer.py:100-186)."""

    def __init__(self, optimizer, named_parameters=None, op=_c.Average,
                 backward_passes_per_step: int = 1,
                 compression=Compression.none):
        self._opt = optimizer
        self._op = op
        self._bpps = backward_passes_per_step
        self._compression = compression
        self._pass_count: Dict[int, int] = {}
        self._handles: Dict[Any, int] = {}
        self._ctxs: Dict[Any, Any] = {}
        self._names: Dict[Any, str] = {}
        all_params = [p for group in optimizer.param_groups
                      for p in group["params"]]
        if named_parameters is not None:
            named = list(named_parameters)
            # every optimizer parameter must be named, or its gradients
            # would silently skip synchronization (reference:
            # torch/optimizer.py:57-62 raises for unnamed parameters)
            named_ids = {id(p) for _, p in named}
            missing = [p for p in all_params if id(p) not in named_ids]
            if missing:
                raise ValueError(
                    "named_parameters was specified, but one or more model "
                    "parameters were not named. Python object ids: " +
                    ", ".join(str(id(p)) for p in missing))
        else:
            named = [(f"param.{gi}.{pi}", p)
                     for gi, group in enumerate(optimizer.param_groups)
                     for pi, p in enumerate(group["params"])]
        seen = set()
        for name, p in named:
            if name in seen:
                raise ValueError(
                    f"duplicate parameter name {name!r} (reference "
                    f"semantics: optimizer.py name dedup)")
            seen.add(name)
            if p.requires_grad:
                self._names[p] = name
                p.register_post_accumulate_grad_hook(self._make_hook())

    # hooks ------------------------------------------------------------------
    def _make_hook(self):
        def hook(p):
            n = self._pass_count.get(id(p), 0) + 1
            self._pass_count[id(p)] = n
            if n >= self._bpps:
                if p in self._handles:
                    raise AssertionError(
                        "Gradients were computed more than "
                        "backward_passes_per_step times before call to "
                        "step(). Increase backward_passes_per_step to "
                        "accumulate gradients locally (reference: "
                        "torch/optimizer.py:122-126).")
                self._pass_count[id(p)] = 0
                grad = _to_numpy(p.grad)
                if self._bpps > 1:
                    grad = grad / self._bpps
                # compress on the wire (reference: torch/optimizer.py:111-117
                # compression hook); decompressed in synchronize()
                compressed, ctx = self._compression.compress(grad)
                self._ctxs[p] = ctx
                self._handles[p] = _c.allreduce_async(
                    compressed, op=self._op,
                    name=f"grad.{self._names[p]}")
        return hook

    # torch optimizer protocol ----------------------------------------------
    def synchronize(self):
        import torch
        if _basics.size() > 1:
            # round marker for cooperative Join (uneven data): joined ranks
            # pair this with their replay loop (collectives.join_round)
            _c.join_round()
        for p, h in list(self._handles.items()):
            out = _synchronize_handle(h)
            out = self._compression.decompress(out, self._ctxs.pop(p, None))
            with torch.no_grad():
                p.grad.copy_(_from_numpy(out, p.grad.dtype))
        self._handles.clear()

    def step(self, closure=None):
        self.synchronize()
        return self._opt.step(closure)

    def zero_grad(self, *a, **kw):
        return self._opt.zero_grad(*a, **kw)

    def state_dict(self, *a, **kw):
        return self._opt.state_dict(*a, **kw)

    def load_state_dict(self, *a, **kw):
        return self._opt.load_state_dict(*a, **kw)

    @property
    def param_groups(self):
        return self._opt.param_groups

    @property
    def state(self):
        return self._opt.state

    def __getattr__(self, item):
        return getattr(self._opt, item)


def DistributedOptimizer(optimizer, named_parameters=None, op=_c.Average,
                         backward_passes_per_step: int = 1,
                         compression=Compression.none):
    return _DistributedOptimizer(
        optimizer, named_parameters=named_parameters, op=op,
        backward_passes_per_step=backward_passes_per_step,
        compression=compression)


def __getattr__(name):  # PEP 562 lazy exports (torch import stays deferred)
    if name == "SyncBatchNorm":
        from .sync_batch_norm import get_sync_batch_norm_class
        return get_sync_batch_norm_class()
    if name == "elastic":
        import importlib
        return importlib.import_module(".elastic", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
