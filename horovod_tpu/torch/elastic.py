"""Elastic state for PyTorch models/optimizers.

Reference: /root/reference/horovod/torch/elastic.py:51-85 — ``TorchState``
holds a model and optimizer, snapshots their ``state_dict()`` to host memory
on ``save()``, rolls back on ``restore()``, and re-seeds restarted workers
from rank 0 on ``sync()`` via parameter/optimizer-state broadcast.
"""

import copy
from typing import Optional

from ..elastic.run import run, run_fn  # noqa: F401  (reference re-export)
from ..elastic.state import ObjectState
from . import broadcast_optimizer_state, broadcast_parameters


class TorchState(ObjectState):
    """Elastic state wrapping a torch model + optimizer plus plain attrs.

    Usage (reference recipe)::

        state = hvd.elastic.TorchState(model, optimizer, epoch=0, batch=0)

        @hvd.elastic.run
        def train(state):
            for epoch in range(state.epoch, epochs):
                ...
                state.commit()
    """

    def __init__(self, model=None, optimizer=None, **kwargs):
        self.model = model
        self.optimizer = optimizer
        self._model_state = (
            copy.deepcopy(model.state_dict()) if model is not None else None)
        self._opt_state = (
            copy.deepcopy(optimizer.state_dict())
            if optimizer is not None else None)
        bcast_object = kwargs.pop("bcast_object", None)
        get_rank = kwargs.pop("get_rank", None)
        super().__init__(bcast_object=bcast_object, get_rank=get_rank,
                         **kwargs)

    def save(self) -> None:
        if self.model is not None:
            self._model_state = copy.deepcopy(self.model.state_dict())
        if self.optimizer is not None:
            self._opt_state = copy.deepcopy(self.optimizer.state_dict())
        super().save()

    def restore(self) -> None:
        if self.model is not None and self._model_state is not None:
            self.model.load_state_dict(self._model_state)
        if self.optimizer is not None and self._opt_state is not None:
            self.optimizer.load_state_dict(self._opt_state)
        super().restore()

    def sync(self) -> None:
        """Broadcast rank 0's live model/optimizer state to every worker,
        then make the synced values the committed snapshot (reference:
        torch/elastic.py TorchState.sync)."""
        if self.model is not None:
            broadcast_parameters(self.model.state_dict(), root_rank=0)
        if self.optimizer is not None:
            broadcast_optimizer_state(self.optimizer, root_rank=0)
        if self.model is not None:
            self._model_state = copy.deepcopy(self.model.state_dict())
        if self.optimizer is not None:
            self._opt_state = copy.deepcopy(self.optimizer.state_dict())
        super().sync()
