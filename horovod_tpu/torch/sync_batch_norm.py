"""Cross-process synchronized BatchNorm for torch models.

Reference: /root/reference/horovod/torch/sync_batch_norm.py — a
``_SyncBatchNorm`` module whose forward gathers per-rank batch statistics and
whose backward allreduces the gradient statistics, so every worker normalizes
with the *global* batch mean/var. The reference builds on CUDA-only kernels
(``torch.batch_norm_stats``/``batch_norm_gather_stats_with_counts``); this
implementation computes the same math directly on CPU tensors (the torch side
of this stack is CPU-resident) and runs the cross-process sums through the
eager XLA collective plane.

Math (identical to the reference's underlying kernels):
  forward:  global mean/var from allreduced (sum, sqsum, count)
  backward: grad_input = (dy - mean(dy) - xhat * mean(dy * xhat)) * invstd * w
            with mean() taken over the GLOBAL batch via allreduce.
"""

from .. import basics as _basics


def _allreduce_sum(t, name: str):
    """Sum-allreduce a 1-D fp32 torch tensor across processes. The name must
    be identical on every process (controller.cc:378-611 validation)."""
    from . import _from_numpy, _to_numpy
    from .. import collectives as _c
    out = _c.allreduce(_to_numpy(t), op=_c.Sum, name=name)
    return _from_numpy(out, t.dtype)


def _make_function():
    import torch

    class _SyncBatchNormFn(torch.autograd.Function):
        @staticmethod
        def forward(ctx, input, weight, bias, eps):
            dims = [0] + list(range(2, input.dim()))
            count = input.numel() // input.size(1)
            f32 = input.float()
            local = torch.cat([
                f32.sum(dims), (f32 * f32).sum(dims),
                torch.tensor([float(count)])])
            glob = _allreduce_sum(local, "sync_bn.fwd_stats")
            c = input.size(1)
            g_sum, g_sqsum, g_count = glob[:c], glob[c:2 * c], glob[2 * c]
            mean = g_sum / g_count
            var = g_sqsum / g_count - mean * mean
            invstd = torch.rsqrt(var + eps)

            shape = [1, c] + [1] * (input.dim() - 2)
            xhat = (f32 - mean.view(shape)) * invstd.view(shape)
            out = xhat
            if weight is not None:
                out = out * weight.float().view(shape)
            if bias is not None:
                out = out + bias.float().view(shape)
            ctx.save_for_backward(xhat, weight, invstd)
            ctx.g_count = g_count
            ctx.mark_non_differentiable(mean, var, g_count)
            return out.to(input.dtype), mean, var, g_count

        @staticmethod
        def backward(ctx, grad_output, _gmean, _gvar, _gcount):
            xhat, weight, invstd = ctx.saved_tensors
            dims = [0] + list(range(2, grad_output.dim()))
            c = grad_output.size(1)
            shape = [1, c] + [1] * (grad_output.dim() - 2)
            dy = grad_output.float()

            grad_weight = (dy * xhat).sum(dims) if weight is not None else None
            grad_bias = dy.sum(dims)

            # global sums of dy and dy*xhat drive grad_input (the reference's
            # batch_norm_backward_elemt math with allreduced mean terms)
            local = torch.cat([dy.sum(dims), (dy * xhat).sum(dims)])
            glob = _allreduce_sum(local, "sync_bn.bwd_stats")
            sum_dy, sum_dy_xhat = glob[:c], glob[c:]
            n = ctx.g_count
            w = weight.float().view(shape) if weight is not None else 1.0
            grad_input = (
                (dy - (sum_dy / n).view(shape)
                 - xhat * (sum_dy_xhat / n).view(shape))
                * invstd.view(shape) * w)
            return (grad_input.to(grad_output.dtype),
                    grad_weight.to(weight.dtype) if weight is not None
                    else None,
                    grad_bias.to(grad_output.dtype), None)

    return _SyncBatchNormFn


_cache = {}


def _fn():
    if "fn" not in _cache:
        _cache["fn"] = _make_function()
    return _cache["fn"]


def get_sync_batch_norm_class():
    if "cls" in _cache:
        return _cache["cls"]
    import torch

    class SyncBatchNorm(torch.nn.modules.batchnorm._BatchNorm):
        """Drop-in BatchNorm whose statistics are synchronized across the
        horovod_tpu world (reference: torch/sync_batch_norm.py
        SyncBatchNorm)."""

        def _check_input_dim(self, input):
            if input.dim() < 2:
                raise ValueError(
                    f"expected at least 2D input (got {input.dim()}D)")

        def forward(self, input):
            self._check_input_dim(input)
            # single process or eval mode: identical to vanilla BatchNorm
            # (reference: falls back when not training or size == 1)
            if not self.training or _basics.size() == 1:
                return super().forward(input)

            out, mean, var, g_count = _fn().apply(
                input, self.weight, self.bias, self.eps)

            if self.track_running_stats:
                with torch.no_grad():
                    unbiased = var * g_count / max(float(g_count) - 1, 1.0)
                    if self.num_batches_tracked is not None:
                        self.num_batches_tracked += 1
                    m = self.momentum
                    if m is None:
                        m = 1.0 / float(self.num_batches_tracked)
                    self.running_mean.mul_(1 - m).add_(
                        mean.to(self.running_mean.dtype) * m)
                    self.running_var.mul_(1 - m).add_(
                        unbiased.to(self.running_var.dtype) * m)
            return out

    _cache["cls"] = SyncBatchNorm
    return SyncBatchNorm
