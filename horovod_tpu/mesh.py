"""Device-mesh management for horovod_tpu.

The reference organizes communication around a GLOBAL/LOCAL/CROSS communicator
triple (/root/reference/horovod/common/common.h:111,
common/mpi/mpi_context.cc:131-156) that enables hierarchical algorithms
(NCCLHierarchicalAllreduce, ops/nccl_operations.cc:178-372). On TPU the same
structure is a ``jax.sharding.Mesh`` whose axes map onto the interconnect:

* ``'proc'``  — one slot per participating process. This is the axis eager
  (host-plane) collectives reduce over; it corresponds to the reference's
  GLOBAL communicator at process granularity.
* within-process devices form the fast inner axis (ICI); cross-host/slice
  traffic rides DCN. Hierarchical allreduce = reduce_scatter(inner) →
  psum(outer) → all_gather(inner), expressed with shard_map in
  :mod:`horovod_tpu.parallel.hierarchical`.

Compiled-plane training uses richer meshes (dp/fsdp/tp/pp/sp/ep) built by
:func:`make_training_mesh` in :mod:`horovod_tpu.parallel.mesh_utils`.
"""

from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PROC_AXIS = "proc"


class WorldMesh:
    """The eager-plane mesh: one anchor device per participating process.

    Eager collectives (allreduce/allgather/broadcast on host values, one value
    per process — the reference's rank granularity) are expressed as jitted
    reductions over the ``'proc'`` axis of this mesh. Remaining local devices
    are not part of the eager plane; they belong to the compiled plane
    (pjit/shard_map over training meshes).
    """

    def __init__(self, devices: Optional[Sequence[jax.Device]] = None):
        if devices is None:
            devices = _anchor_devices()
        self._devices: List[jax.Device] = list(devices)
        self.mesh = Mesh(np.array(self._devices), (PROC_AXIS,))
        self.num_procs = len(self._devices)
        # Stable cache key for compiled collective programs (id(mesh) could
        # be reused after GC of an ephemeral subset mesh).
        self.cache_key = tuple(d.id for d in self._devices)
        local = set(d.id for d in jax.local_devices())
        self._my_index = next(
            (i for i, d in enumerate(self._devices) if d.id in local), -1)

    @property
    def is_member(self) -> bool:
        return self._my_index >= 0

    @property
    def anchor_device(self) -> jax.Device:
        if self._my_index < 0:
            raise ValueError(
                "this process has no device in the mesh/process set; only "
                "member processes may call collectives on it")
        return self._devices[self._my_index]

    @property
    def my_index(self) -> int:
        if self._my_index < 0:
            raise ValueError(
                "this process has no device in the mesh/process set; only "
                "member processes may call collectives on it")
        return self._my_index

    def stacked_sharding(self) -> NamedSharding:
        """Sharding for a (num_procs, ...) array with one row per process."""
        return NamedSharding(self.mesh, P(PROC_AXIS))

    def replicated_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def subset(self, proc_indices: Sequence[int]) -> "WorldMesh":
        """Sub-mesh over a subset of processes (reference: process sets via
        hvd.init(ranks), basics.py:33-65, operations.cc:624-628)."""
        return WorldMesh([self._devices[i] for i in proc_indices])


def _anchor_devices() -> List[jax.Device]:
    """First local device of each process, ordered by process index.

    With one process (the common TPU single-controller case) this is just
    ``[devices[0]]``; with N processes it yields one device per process.
    """
    devices = jax.devices()
    by_proc = {}
    for d in devices:
        by_proc.setdefault(d.process_index, d)
    return [by_proc[p] for p in sorted(by_proc)]


def full_mesh(axis_name: str = "world") -> Mesh:
    """A 1-D mesh over every addressable device, device-granular.

    This is the axis data-parallel compiled training reduces over — the
    TPU-native analogue of the reference's world communicator at GPU
    granularity.
    """
    return Mesh(np.array(jax.devices()), (axis_name,))
