"""horovod_tpu: a TPU-native distributed training framework.

Brand-new implementation of the capabilities of Horovod (reference:
wwiiiii/horovod v0.19.2-dev) designed for TPU hardware: the data plane is XLA
collectives over ICI/DCN driven by jit/pjit/shard_map over device meshes, the
host plane is a light coordination layer (rendezvous, elastic membership,
timeline, stall detection), and the hot paths are Pallas kernels. See
SURVEY.md at the repo root for the structural mapping to the reference.

Quick start (data-parallel, single controller)::

    import horovod_tpu as hvd
    hvd.init()
    # compiled plane: shard the batch over all chips, wrap the optimizer
    opt = hvd.DistributedOptimizer(optax.adam(1e-3 * hvd.dp_size()))

Eager host-plane collectives (one value per process, reference rank
semantics)::

    out = hvd.allreduce(x, name="x")          # average across processes
    gat = hvd.allgather(x)                    # concat along dim 0
    y   = hvd.broadcast(x, root_rank=0)
"""

__version__ = "0.1.0"

from .basics import (  # noqa: F401
    init, shutdown, is_initialized,
    rank, size, local_rank, local_size, cross_rank, cross_size,
    device_count, local_device_count, dp_size, is_homogeneous,
    process_set_mesh, hostname,
    xla_built, tpu_available, mpi_built, mpi_enabled, gloo_built,
    nccl_built, ccl_built, ddl_built, cuda_built, rocm_built,
    mpi_threads_supported,
)
from .collectives import (  # noqa: F401
    ReduceOp, Average, Sum, Adasum, Min, Max, Product,
    allreduce, allreduce_async, grouped_allreduce, grouped_allreduce_async,
    allgather, allgather_async,
    broadcast, broadcast_async, grouped_broadcast, grouped_broadcast_async,
    alltoall, alltoall_async,
    poll, synchronize, release, join, join_round, joined, barrier,
)
from .timeline import (  # noqa: F401
    start_jax_profiler, stop_jax_profiler,
)
from .exceptions import (  # noqa: F401
    HorovodInternalError, HostsUpdatedInterrupt, TensorValidationError,
    DuplicateNameError, NotInitializedError, StallError,
)


def __getattr__(name):
    # Lazy surface for heavier subsystems so `import horovod_tpu` stays cheap.
    if name in ("metrics_snapshot", "metrics_allgather_summary"):
        from . import metrics
        return {"metrics_snapshot": metrics.snapshot,
                "metrics_allgather_summary":
                    metrics.metrics_allgather_summary}[name]
    if name in ("metrics", "faults", "retry"):
        import importlib
        return importlib.import_module("." + name, __name__)
    if name in ("DistributedOptimizer", "DistributedGradientTransform"):
        from . import optimizer
        return getattr(optimizer, name)
    if name in ("broadcast_parameters", "broadcast_object",
                "broadcast_optimizer_state", "allgather_object"):
        from . import functions
        return getattr(functions, name)
    if name == "Compression":
        from .compression import Compression
        return Compression
    if name in ("SyncBatchNorm", "sync_batch_norm_stats"):
        from . import sync_batch_norm
        return getattr(sync_batch_norm, name)
    if name in ("SparseGradient", "allreduce_sparse",
                "allreduce_sparse_as_dense", "sparse_to_dense"):
        from . import sparse
        return getattr(sparse, name)
    if name == "Estimator":
        from .estimator import Estimator
        return Estimator
    if name in ("callbacks", "torch", "data", "checkpoint", "checkpointing",
                "serving", "tensorflow", "keras", "spark"):
        # importlib, not `from . import x`: the fromlist lookup re-enters
        # this __getattr__ before sys.modules is populated (see `elastic`)
        import importlib
        return importlib.import_module("." + name, __name__)
    if name == "elastic":
        # NOT `from . import elastic`: the fromlist lookup re-enters this
        # __getattr__ before sys.modules is populated -> infinite recursion.
        import importlib
        return importlib.import_module(".elastic", __name__)
    raise AttributeError(f"module 'horovod_tpu' has no attribute {name!r}")
