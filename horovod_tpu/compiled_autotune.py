"""Compiled-plane autotuning: pick the fastest program variant by
measurement, identically on every process.

The reference tunes its hot path online — fusion threshold, cycle time,
cache, hierarchical-allreduce on/off — scored on measured throughput, with
rank 0's choice broadcast to all workers
(/root/reference/horovod/common/parameter_manager.h:33-105,
controller.cc:33-47 SynchronizeParameters). On TPU the hot path is a
compiled XLA program: there is no per-cycle knob to nudge, but the SAME
decision exists one level up — *which program to compile*. The tunable
surface here:

* reduction strategy per mesh axis: ``hierarchical`` (inner-axis mean
  first — rides ICI — then the outer axis, the NCCLHierarchicalAllreduce
  shape, nccl_operations.cc:178-372) vs ``flat`` (one collective over all
  axes);
* gradient packing: ``per_leaf`` (one psum per gradient, XLA's collective
  combiner fuses) vs ``packed`` (explicit flat buffer per dtype — the
  fusion-buffer shape, fusion_buffer_manager.h:30-55).

Protocol: every process times each variant in the same deterministic
order (variants are collectives — all processes must run them in
lockstep), then rank 0's fastest is broadcast and adopted everywhere, so
all processes end up compiling the identical program.

The eager-plane fusion threshold keeps its own online tuner
(parameter_manager.py); this module is its compiled-plane sibling.
"""

import time
from typing import Callable, Dict, Sequence, Tuple

import numpy as np

from . import basics as _basics
from . import collectives as _c
from . import metrics as _metrics

_M_VARIANTS = _metrics.counter(
    "hvd_tpu_autotune_compiled_variants_total",
    "Compiled-plane program variants measured by autotune_variants().")
_M_TUNES = _metrics.counter(
    "hvd_tpu_autotune_compiled_tunes_total",
    "Completed compiled-plane tuning rounds (one variant adopted "
    "world-wide per round).")


def autotune_variants(variants: Dict[str, Callable], args: Sequence = (),
                      warmup: int = 1, iters: int = 3,
                      key: str = "default"
                      ) -> Tuple[str, Callable, Dict[str, float]]:
    """Measure each variant and return ``(chosen_name, chosen_fn, times)``.

    Variants run in sorted-name order on every process (they may contain
    collectives, so the order must be identical everywhere). The choice is
    rank 0's measured argmin, broadcast so every process adopts the same
    variant (reference: SynchronizeParameters, controller.cc:33-47).
    """
    import jax
    if not variants:
        raise ValueError("no variants to tune over")
    names = sorted(variants)
    times: Dict[str, float] = {}
    for n in names:
        fn = variants[n]
        for _ in range(max(0, warmup)):
            jax.block_until_ready(fn(*args))
        t0 = time.perf_counter()
        for _ in range(max(1, iters)):
            jax.block_until_ready(fn(*args))
        times[n] = (time.perf_counter() - t0) / max(1, iters)
        _M_VARIANTS.inc()
    best_idx = names.index(min(names, key=lambda n: times[n]))
    w = _basics.world()
    if w.num_processes > 1:
        out = _c.broadcast(np.array([best_idx], np.int32), root_rank=0,
                           name=f"hvd_tpu.autotune.compiled.{key}")
        best_idx = int(np.asarray(out)[0])
    chosen = names[best_idx]
    _M_TUNES.inc()
    _log_choice(w, key, chosen, times)
    return chosen, variants[chosen], times


def _log_choice(w, key: str, chosen: str, times: Dict[str, float]) -> None:
    from . import config as _config
    path = w.config.get(_config.AUTOTUNE_LOG)
    if not path or w.process_id != 0:
        return
    try:
        with open(path, "a") as f:
            f.write(f"{time.strftime('%Y-%m-%d %H:%M:%S')} compiled[{key}] "
                    f"chose {chosen}; times="
                    + ", ".join(f"{k}={v:.6f}s" for k, v in
                                sorted(times.items())) + "\n")
    except OSError:
        pass


def tune_distributed_step(make_step: Callable[..., Callable],
                          args: Sequence = (),
                          strategies: Sequence[str] = ("hierarchical",
                                                       "flat"),
                          packings: Sequence[str] = ("per_leaf", "packed"),
                          warmup: int = 1, iters: int = 3,
                          key: str = "train_step"
                          ) -> Tuple[dict, Callable]:
    """Tune a training step over the compiled-plane reduction options.

    ``make_step(reduce_strategy=..., packing=...)`` must return a callable
    (typically a fresh ``jax.jit`` of a step built around a
    ``DistributedOptimizer`` constructed with those options). Every
    combination is compiled and measured; the fastest (rank-0-adopted)
    wins. Returns ``({"reduce_strategy": s, "packing": p}, step_fn)``.

    Example::

        def make_step(reduce_strategy, packing):
            opt = hvd.DistributedOptimizer(
                optax.sgd(0.01), axis_name="dp", inner_axis="ici",
                reduce_strategy=reduce_strategy, packing=packing)
            ... build and jit the step ...
            return step
        options, step = tune_distributed_step(make_step, (params, batch))
    """
    variants = {
        f"{s}/{p}": make_step(reduce_strategy=s, packing=p)
        for s in strategies for p in packings}
    chosen, fn, times = autotune_variants(
        variants, args, warmup=warmup, iters=iters, key=key)
    s, p = chosen.split("/", 1)
    return {"reduce_strategy": s, "packing": p}, fn
