"""Keras interop: Keras-3 models trained with the TPU-hosted collective
plane.

Reference surface: horovod/keras + horovod/_keras
(/root/reference/horovod/keras/__init__.py — DistributedOptimizer wrapping
get_gradients; _keras/callbacks.py:22-190 — the callback family). With
Keras 3, gradient interception moved to ``apply_gradients``
(:func:`DistributedOptimizer` from the tensorflow module handles it); this
module supplies the callbacks as real ``keras.callbacks.Callback``
subclasses so they plug into ``model.fit``.

Usage::

    import horovod_tpu.keras as hvd
    hvd.init()
    model.compile(optimizer=hvd.DistributedOptimizer(opt), ...)
    model.fit(x, y, callbacks=[
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
    ])
"""

from ..basics import (  # noqa: F401
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
)
from ..collectives import Average, Sum, Adasum  # noqa: F401
from ..tensorflow import (  # noqa: F401
    DistributedOptimizer, allreduce, allgather, broadcast,
    broadcast_variables,
)

from . import callbacks  # noqa: F401  (module at the end: imports keras)
