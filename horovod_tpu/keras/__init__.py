"""Keras interop: Keras-3 models trained with the TPU-hosted collective
plane.

Reference surface: horovod/keras + horovod/_keras
(/root/reference/horovod/keras/__init__.py — DistributedOptimizer wrapping
get_gradients; _keras/callbacks.py:22-190 — the callback family). With
Keras 3, gradient interception moved to ``apply_gradients``
(:func:`DistributedOptimizer` from the tensorflow module handles it); this
module supplies the callbacks as real ``keras.callbacks.Callback``
subclasses so they plug into ``model.fit``.

Usage::

    import horovod_tpu.keras as hvd
    hvd.init()
    model.compile(optimizer=hvd.DistributedOptimizer(opt), ...)
    model.fit(x, y, callbacks=[
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
    ])
"""

from ..basics import (  # noqa: F401
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
)
from ..collectives import Average, Sum, Adasum  # noqa: F401
from ..tensorflow import (  # noqa: F401
    DistributedOptimizer, allreduce, allgather, broadcast,
    broadcast_variables,
)

from . import callbacks  # noqa: F401  (module at the end: imports keras)


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=None):
    """Load a saved Keras model with its optimizer wrapped in
    ``DistributedOptimizer`` so retraining reduces gradients (reference:
    horovod/keras/__init__.py:117-145 load_model).

    The reference wraps optimizer CLASSES during deserialization (its
    wrapper is a dynamic subclass that must round-trip through Keras's
    object registry); this bridge's wrapper patches ``apply_gradients``
    on the live optimizer INSTANCE, so the model loads normally —
    optimizer state (slots, iterations) included — and the deserialized
    optimizer is wrapped afterwards. ``custom_optimizers`` therefore
    only needs to make the classes visible to deserialization; wrapping
    is unconditional.
    """
    import keras
    objects = dict(custom_objects or {})
    for cls in custom_optimizers or ():
        objects.setdefault(cls.__name__, cls)
    model = keras.models.load_model(filepath, custom_objects=objects)
    if getattr(model, "optimizer", None) is not None:
        DistributedOptimizer(model.optimizer, compression=compression)
    return model
