"""Keras callbacks backed by the TPU collective plane.

Reference: /root/reference/horovod/_keras/callbacks.py:22-190. These are
``keras.callbacks.Callback`` subclasses for ``model.fit``; the
framework-neutral equivalents for hand-written flax loops live in
:mod:`horovod_tpu.callbacks`.
"""

import numpy as np

import keras

from .. import collectives as _c


class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
    """Broadcast all model/optimizer weights from root once, on the first
    batch — so checkpoint restores that happen after callback construction
    still win (reference: _keras/callbacks.py:22-46)."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self._done = False

    def on_train_batch_end(self, batch, logs=None):
        if self._done:
            return
        from ..tensorflow import broadcast_variables
        broadcast_variables(self.model.weights, root_rank=self.root_rank)
        opt_vars = getattr(self.model.optimizer, "variables", None)
        if opt_vars:
            vars_ = opt_vars() if callable(opt_vars) else opt_vars
            broadcast_variables(list(vars_), root_rank=self.root_rank)
        self._done = True


class MetricAverageCallback(keras.callbacks.Callback):
    """Average epoch-end metrics across processes, in place, in sorted
    order (reference: _keras/callbacks.py:48-87)."""

    def on_epoch_end(self, epoch, logs=None):
        from ..callbacks import average_logs
        average_logs(logs, "keras.metric")


class LearningRateScheduleCallback(keras.callbacks.Callback):
    """Multiply the optimizer LR by ``multiplier(epoch)`` within
    [start_epoch, end_epoch) (reference: _keras/callbacks.py:90-166)."""

    def __init__(self, initial_lr: float, multiplier, start_epoch: int = 0,
                 end_epoch=None, staircase: bool = True,
                 steps_per_epoch=None):
        super().__init__()
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.steps_per_epoch = steps_per_epoch
        if not callable(multiplier):
            self.staircase = True
            self.multiplier = lambda e: multiplier
        else:
            self.staircase = staircase
            self.multiplier = multiplier
        self._epoch = 0

    def _set_lr(self, epoch_like: float):
        self.model.optimizer.learning_rate.assign(
            self.initial_lr * self.multiplier(epoch_like))

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch

    def on_train_batch_begin(self, batch, logs=None):
        if self._epoch < self.start_epoch or (
                self.end_epoch is not None and self._epoch >= self.end_epoch):
            return
        if self.staircase:
            if batch == 0:
                self._set_lr(self._epoch)
        else:
            spe = self.steps_per_epoch or self.params.get("steps")
            if not spe:
                raise ValueError(
                    "non-staircase schedules need steps_per_epoch "
                    "(reference: _autodetect_steps_per_epoch)")
            self._set_lr(self._epoch + batch / spe)

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs["lr"] = float(
                np.asarray(self.model.optimizer.learning_rate))


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual warmup from base LR to size()-scaled LR over
    ``warmup_epochs`` (reference: _keras/callbacks.py:169-190)."""

    def __init__(self, initial_lr: float, warmup_epochs: float = 5,
                 steps_per_epoch=None, verbose: int = 0):
        from .. import basics

        def multiplier(epoch):
            n = basics.dp_size() if basics.is_initialized() else 1
            spe = self.steps_per_epoch or self.params.get("steps") or 1
            epoch += 1.0 / spe
            return 1.0 / n * (epoch * (n - 1) / warmup_epochs + 1)

        super().__init__(initial_lr, multiplier, start_epoch=0,
                         end_epoch=warmup_epochs, staircase=False,
                         steps_per_epoch=steps_per_epoch)
        self.verbose = verbose
