"""Collective-plane microbenchmarks.

The reference treats eager collective dispatch as its hot loop — fused
buffers (fusion_buffer_manager.h:30-55), a 5 ms negotiation cycle, and a
finalizer pool that pipelines back-to-back NCCL launches
(gpu_operations.cc:60-87). Our eager plane replaces all of that with one
jitted XLA reduction per dispatch, staging host values to the device on
the way in. This module measures that design instead of assuming it:

* :func:`eager_sweep` — payload sweep (1 KB → 256 MB) of the eager
  ``allreduce`` / ``grouped_allreduce`` path, reporting bytes/sec, the
  async dispatch latency (time for ``allreduce_async`` to return to the
  caller), and the ratio against an **in-jit** reduction of the very same
  global payload with pre-staged device inputs. The gap between the two
  IS the eager plane's staging + host-dispatch overhead — the quantity
  the reference's fusion buffer exists to amortize.
* :func:`scaling_sweep_point` — compiled-data-plane train step (the same
  DistributedOptimizer path ``bench.py`` measures) over every visible
  device, reporting throughput for one device count. The driver script
  (``microbench.py`` at the repo root) sweeps 1→8 virtual CPU devices and
  computes scaling efficiency — exercising the measurement machinery a
  real pod run needs (virtual CPU devices share host cores, so the CPU
  efficiency trend is a machinery check, not a performance claim).

Results are written to ``MICROBENCH.json`` by the root script and cited
in ``docs/tensor-fusion.md``.
"""

import time
from typing import List, Optional, Sequence

import numpy as np

# Payload ladder: 1 KB → 256 MB (reference fusion threshold is 64 MB;
# common.h:95). The top sizes are where bandwidth dominates, the bottom
# where per-dispatch overhead dominates.
DEFAULT_SIZES = (1 << 10, 1 << 14, 1 << 17, 1 << 20, 1 << 23, 1 << 26,
                 1 << 28)


def _timeit(fn, iters: int, warmup: int = 1) -> float:
    """Median wall-clock seconds of ``fn()`` over ``iters`` runs."""
    for _ in range(warmup):
        fn()
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def eager_sweep(sizes: Sequence[int] = DEFAULT_SIZES, iters: int = 5,
                group: int = 8) -> List[dict]:
    """Sweep eager collectives over payload sizes. Must run inside an
    initialized world (any process count); every rank executes the same
    sequence (SPMD lockstep), results are identical across ranks."""
    import jax
    import jax.numpy as jnp

    import horovod_tpu as hvd
    from . import collectives

    w = collectives._world()
    wm = w.world_mesh
    nproc = wm.num_procs
    results = []

    for size in sizes:
        n_el = max(1, size // 4)
        x = np.ones((n_el,), np.float32)
        payload = n_el * 4

        # --- eager allreduce: full round trip, host in → host-visible out.
        def run_allreduce():
            out = hvd.allreduce(x, op=hvd.Sum, name=f"mb_ar_{size}")
            np.asarray(out)  # force the result all the way back

        t_eager = _timeit(run_allreduce, iters)

        # --- async dispatch latency: how long the caller thread is blocked
        # per submission (the reference's EnqueueTensorAllreduce cost).
        handles = []

        def run_dispatch():
            t0 = time.perf_counter()
            h = hvd.allreduce_async(x, op=hvd.Sum, name=f"mb_ard_{size}")
            dt = time.perf_counter() - t0
            handles.append((h, dt))

        lat = []
        for _ in range(iters):
            run_dispatch()
            h, dt = handles.pop()
            lat.append(dt)
            hvd.synchronize(h)
        t_dispatch = float(np.median(lat))

        # --- grouped allreduce: ``group`` tensors fused into one dispatch.
        chunk = max(1, n_el // group)
        xs = [np.ones((chunk,), np.float32) for _ in range(group)]

        def run_grouped():
            outs = hvd.grouped_allreduce(xs, op=hvd.Sum,
                                         name=f"mb_gar_{size}")
            np.asarray(outs[0])

        t_grouped = _timeit(run_grouped, iters)

        # --- in-jit reduction of the SAME global payload with inputs
        # already staged on device: the compiled-plane cost floor. The
        # program is identical to the eager plane's (sum over the proc
        # axis); only staging and per-call host work differ.
        stacked = collectives._global_from_local(wm, x)
        if nproc > 1:
            injit = jax.jit(lambda g: jnp.sum(g, axis=0),
                            out_shardings=wm.replicated_sharding())
        else:
            injit = jax.jit(lambda g: jnp.sum(g, axis=0))

        def run_injit():
            injit(stacked).block_until_ready()

        t_injit = _timeit(run_injit, iters)

        results.append({
            "payload_bytes": payload,
            "nproc": nproc,
            "eager_allreduce_s": t_eager,
            "eager_bytes_per_s": payload / t_eager,
            "dispatch_latency_s": t_dispatch,
            "grouped_allreduce_s": t_grouped,
            "grouped_bytes_per_s": (chunk * 4 * group) / t_grouped,
            "injit_reduce_s": t_injit,
            "eager_over_injit": t_eager / t_injit if t_injit > 0 else None,
        })
    return results


def scaling_sweep_point(batch_per_device: int = 8, image_size: int = 32,
                        model_name: str = "resnet18",
                        num_iters: int = 3,
                        num_batches_per_iter: int = 5) -> dict:
    """One point of the compiled-plane scaling sweep: DP train step over
    every visible device (the bench.py data plane), returning throughput.
    The root script runs this under 1/2/4/8 virtual CPU devices and
    derives efficiency = T(n) / (n * T(1))."""
    import jax

    from .benchmark import _Rig

    rig = _Rig(batch_per_device, image_size, model_name, "sgd")
    r = rig.run_stage(num_warmup_batches=2,
                      num_batches_per_iter=num_batches_per_iter,
                      num_iters=num_iters)
    return {
        "num_devices": r.num_chips,
        "batch_per_device": r.batch_per_chip,
        "images_per_sec_total": r.images_per_sec_total,
        "images_per_sec_per_device": r.images_per_sec_per_chip,
        "platform": r.platform,
    }
