"""Collective-plane microbenchmarks.

The reference treats eager collective dispatch as its hot loop — fused
buffers (fusion_buffer_manager.h:30-55), a 5 ms negotiation cycle, and a
finalizer pool that pipelines back-to-back NCCL launches
(gpu_operations.cc:60-87). Our eager plane replaces all of that with one
jitted XLA reduction per dispatch, staging host values to the device on
the way in. This module measures that design instead of assuming it:

* :func:`eager_sweep` — payload sweep (1 KB → 256 MB) of the eager
  ``allreduce`` / ``grouped_allreduce`` path, reporting bytes/sec, the
  async dispatch latency (time for ``allreduce_async`` to return to the
  caller), and the ratio against an **in-jit** reduction of the very same
  global payload with pre-staged device inputs. The gap between the two
  IS the eager plane's staging + host-dispatch overhead — the quantity
  the reference's fusion buffer exists to amortize.
* :func:`scaling_sweep_point` — compiled-data-plane train step (the same
  DistributedOptimizer path ``bench.py`` measures) over every visible
  device, reporting throughput for one device count. The driver script
  (``microbench.py`` at the repo root) sweeps 1→8 virtual CPU devices and
  computes scaling efficiency — exercising the measurement machinery a
  real pod run needs (virtual CPU devices share host cores, so the CPU
  efficiency trend is a machinery check, not a performance claim).

Results are written to ``MICROBENCH.json`` by the root script and cited
in ``docs/tensor-fusion.md``.
"""

import time
from typing import List, Optional, Sequence

import numpy as np

# Payload ladder: 1 KB → 256 MB (reference fusion threshold is 64 MB;
# common.h:95). The top sizes are where bandwidth dominates, the bottom
# where per-dispatch overhead dominates.
DEFAULT_SIZES = (1 << 10, 1 << 14, 1 << 17, 1 << 20, 1 << 23, 1 << 26,
                 1 << 28)


def _timeit(fn, iters: int, warmup: int = 1) -> float:
    """Best wall-clock seconds of ``fn()`` over ``iters`` runs (min, the
    ``timeit`` convention: outside interference only ever adds time, so
    the minimum is the least-noisy estimate of the code's cost — medians
    of CPU-backend collective runs flapped 3x between identical
    configurations in round 5)."""
    for _ in range(warmup):
        fn()
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return float(np.min(ts))


def eager_sweep(sizes: Sequence[int] = DEFAULT_SIZES, iters: int = 5,
                group: int = 8) -> List[dict]:
    """Sweep eager collectives over payload sizes. Must run inside an
    initialized world (any process count); every rank executes the same
    sequence (SPMD lockstep), results are identical across ranks."""
    import jax
    import jax.numpy as jnp

    import horovod_tpu as hvd
    from . import collectives

    w = collectives._world()
    wm = w.world_mesh
    nproc = wm.num_procs
    results = []

    for size in sizes:
        n_el = max(1, size // 4)
        x = np.ones((n_el,), np.float32)
        payload = n_el * 4
        # More rounds at the cheap sizes: the box this runs on shares
        # cores, so per-round load swings dominate small payloads
        rounds = iters if payload > (8 << 20) else max(iters, 12)

        # --- eager allreduce: full round trip, host in → host-visible out.
        def run_allreduce():
            out = hvd.allreduce(x, op=hvd.Sum, name=f"mb_ar_{size}")
            np.asarray(out)  # force the result all the way back

        # --- grouped allreduce: ``group`` tensors fused into one dispatch.
        chunk = max(1, n_el // group)
        xs = [np.ones((chunk,), np.float32) for _ in range(group)]

        def run_grouped():
            outs = hvd.grouped_allreduce(xs, op=hvd.Sum,
                                         name=f"mb_gar_{size}")
            np.asarray(outs[0])

        # --- in-jit reduction of the SAME global payload with inputs
        # already staged on device: the compiled-plane cost floor. The
        # program is identical to the eager plane's (sum over the proc
        # axis); only staging and per-call host work differ.
        stacked = collectives._global_from_local(wm, x)
        if nproc > 1:
            injit = jax.jit(lambda g: jnp.sum(g, axis=0),
                            out_shardings=wm.replicated_sharding())
        else:
            injit = jax.jit(lambda g: jnp.sum(g, axis=0))

        def run_injit():
            injit(stacked).block_until_ready()

        # The timed variants are INTERLEAVED round-robin (a full round of
        # single/grouped/injit/dispatch per iteration) so shared-machine
        # load swings hit every variant alike; each variant's estimate is
        # its best round (_timeit convention). Sequential per-variant
        # timing flapped 3x between identical runs in round 5.
        run_allreduce(), run_grouped(), run_injit()  # warmup/compile
        t_eager = t_grouped = t_injit = float("inf")
        lat = []
        for _ in range(rounds):
            t0 = time.perf_counter()
            run_allreduce()
            t_eager = min(t_eager, time.perf_counter() - t0)
            t0 = time.perf_counter()
            run_grouped()
            t_grouped = min(t_grouped, time.perf_counter() - t0)
            t0 = time.perf_counter()
            run_injit()
            t_injit = min(t_injit, time.perf_counter() - t0)
            # async dispatch latency: how long the caller thread is
            # blocked per submission (EnqueueTensorAllreduce cost).
            t0 = time.perf_counter()
            h = hvd.allreduce_async(x, op=hvd.Sum, name=f"mb_ard_{size}")
            lat.append(time.perf_counter() - t0)
            hvd.synchronize(h)
        t_dispatch = float(np.median(lat))

        results.append({
            "payload_bytes": payload,
            "nproc": nproc,
            "eager_allreduce_s": t_eager,
            "eager_bytes_per_s": payload / t_eager,
            "dispatch_latency_s": t_dispatch,
            "grouped_allreduce_s": t_grouped,
            "grouped_bytes_per_s": (chunk * 4 * group) / t_grouped,
            "injit_reduce_s": t_injit,
            "eager_over_injit": t_eager / t_injit if t_injit > 0 else None,
        })
    return results


def resnet50_grad_shapes() -> List[tuple]:
    """ResNet-50's 161 parameter shapes (~25.5M params, ~102 MB fp32) —
    the realistic parameter set the fusion-threshold default was designed
    around (reference: HOROVOD_FUSION_THRESHOLD=64MB, common.h:95, tuned
    on exactly this model per docs/benchmarks.rst)."""
    shapes = [(7, 7, 3, 64), (64,), (64,)]
    c_in = 64
    for blocks, cmid, cout in ((3, 64, 256), (4, 128, 512),
                               (6, 256, 1024), (3, 512, 2048)):
        for b in range(blocks):
            shapes += [(1, 1, c_in, cmid), (cmid,), (cmid,),
                       (3, 3, cmid, cmid), (cmid,), (cmid,),
                       (1, 1, cmid, cout), (cout,), (cout,)]
            if b == 0:
                shapes += [(1, 1, c_in, cout), (cout,), (cout,)]
            c_in = cout
    shapes += [(2048, 1000), (1000,)]
    return shapes


def bucketed_optimizer_sweep(iters: int = 5,
                             threshold_mb: int = 64) -> dict:
    """Per-parameter dispatch vs bucketed grouped dispatch over a full
    ResNet-50 gradient set at the default fusion threshold — the
    end-to-end claim behind tensor fusion (reference
    collective_operations.cc:37-81): a backward pass issuing one
    allreduce per parameter pays ~161 dispatch+staging roundtrips;
    bucketing pays ceil(total/threshold) grouped ones."""
    import horovod_tpu as hvd
    from .fusion import plan_buckets

    shapes = resnet50_grad_shapes()
    grads = [np.ones(s, np.float32) for s in shapes]
    total_bytes = sum(g.nbytes for g in grads)
    buckets = plan_buckets([(s, np.float32) for s in shapes],
                           threshold_mb * (1 << 20))

    def run_per_param():
        hs = [hvd.allreduce_async(g, op=hvd.Sum, name=f"mb_pp_{i}")
              for i, g in enumerate(grads)]
        outs = [hvd.synchronize(h) for h in hs]
        np.asarray(outs[-1])

    def run_bucketed():
        hs = [hvd.grouped_allreduce_async(
                  [grads[i] for i in b], op=hvd.Sum, name=f"mb_bk_{j}")
              for j, b in enumerate(buckets)]
        outs = [hvd.synchronize(h) for h in hs]
        np.asarray(outs[-1][-1])

    # interleaved A/B rounds, best-round estimates (see eager_sweep)
    run_per_param(), run_bucketed()
    t_pp = t_bk = float("inf")
    for _ in range(max(iters, 5)):
        t0 = time.perf_counter()
        run_per_param()
        t_pp = min(t_pp, time.perf_counter() - t0)
        t0 = time.perf_counter()
        run_bucketed()
        t_bk = min(t_bk, time.perf_counter() - t0)
    return {
        "scenario": "resnet50_bucketed_optimizer",
        "num_grads": len(grads),
        "total_mb": round(total_bytes / (1 << 20), 1),
        "threshold_mb": threshold_mb,
        "num_buckets": len(buckets),
        "per_param_s": t_pp,
        "bucketed_s": t_bk,
        "bucketed_speedup": round(t_pp / t_bk, 2) if t_bk > 0 else None,
    }


def _shard_map():
    """Version-tolerant shard_map with replication checking disabled
    (all_gather-based lowerings — broadcast, int8 — fail the static
    replication inference on some jax versions). Public ``jax.shard_map``
    landed after the jax this container ships (the experimental path is
    the same function), and ``check_rep`` was renamed ``check_vma`` in
    newer jax — tolerate both, or the sweep's variants all die and the
    ``injit`` MICROBENCH section silently goes empty."""
    import jax
    try:
        smap = jax.shard_map
    except AttributeError:
        from jax.experimental.shard_map import shard_map as smap

    def wrap(f, **kw):
        try:
            return smap(f, check_rep=False, **kw)
        except TypeError:  # renamed in newer jax
            return smap(f, check_vma=False, **kw)
    return wrap


def injit_optimizer_sweep(iters: int = 5) -> dict:
    """The compiled-plane fast path on the ResNet-50 161-gradient
    scenario (docs/injit.md): per-leaf vs packed vs packed+bf16 vs
    packed+int8 ``DistributedGradientTransform.update`` under shard_map
    over every visible device, inputs pre-staged (the reduction cost, not
    host transfer). This is the in-jit counterpart of
    :func:`bucketed_optimizer_sweep` — the same gradient set the eager
    bucketed path dispatches in ~161 host roundtrips runs here as a
    handful of fused XLA collectives, which is the ROADMAP item 2 claim
    MICROBENCH.json exists to keep honest.

    ``wire_mb`` is the analytic per-device payload entering the
    collectives (fp32 x4 / bf16 x2 / int8 x1 bytes per element; fp16's
    upcast-psum would put fp32 back on the wire, which is why bf16 is the
    headline half — compression.py).
    """
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from .compression import Compression
    from .fusion import packed_plan
    from .optimizer import _packed_threshold

    shard_map = _shard_map()
    devices = jax.devices()
    n = len(devices)
    mesh = Mesh(np.array(devices), ("dp",))

    shapes = resnet50_grad_shapes()
    names = [f"g{i}" for i in range(len(shapes))]
    params = {k: jnp.zeros(s, jnp.float32) for k, s in zip(names, shapes)}
    rng = np.random.RandomState(0)
    grads_host = {
        k: np.stack([rng.standard_normal(s).astype(np.float32) * (d + 1)
                     for d in range(n)])
        for k, s in zip(names, shapes)}
    shard = NamedSharding(mesh, P("dp"))
    grads = {k: jax.device_put(v, shard) for k, v in grads_host.items()}
    total_bytes = sum(int(np.prod(s, dtype=np.int64)) * 4 for s in shapes)
    threshold = _packed_threshold()
    plan = packed_plan([(1,) + tuple(s) for s in shapes],
                       ["float32"] * len(shapes), threshold)

    def make_variant(packing, compression):
        opt = hvd.DistributedOptimizer(
            optax.identity(), axis_name="dp", packing=packing,
            compression=compression)
        state = opt.init(params)
        stateful = getattr(compression, "stateful", False)
        if stateful:
            def step(g, st):
                return opt.update(g, st, params)
            f = jax.jit(shard_map(
                step, mesh=mesh, in_specs=(P("dp"), P()),
                out_specs=(P("dp"), P())))
            box = {"state": state}

            def run():
                u, box["state"] = f(grads, box["state"])
                jax.block_until_ready(u)
                return u
        else:
            def step(g):
                u, _ = opt.update(g, state, params)
                return u
            f = jax.jit(shard_map(step, mesh=mesh, in_specs=P("dp"),
                                  out_specs=P("dp")))

            def run():
                u = f(grads)
                jax.block_until_ready(u)
                return u
        return run

    elem_bytes = {"per_leaf": 4, "packed": 4, "packed_bf16": 2,
                  "packed_int8": 1}
    variants = {
        "per_leaf": make_variant("per_leaf", Compression.none),
        "packed": make_variant("packed", Compression.none),
        "packed_bf16": make_variant("packed", Compression.bf16),
        "packed_int8": make_variant("packed", Compression.int8),
    }

    # warmup/compile + numerics reference off the first calls
    firsts = {k: run() for k, run in variants.items()}
    ref = firsts["per_leaf"]

    def max_err(u):
        return max(float(jnp.max(jnp.abs(u[k].astype(jnp.float32)
                                         - ref[k].astype(jnp.float32))))
                   for k in names)

    errs = {k: max_err(firsts[k]) for k in variants if k != "per_leaf"}
    # interleaved round-robin, best-round estimates (see eager_sweep)
    best = {k: float("inf") for k in variants}
    for _ in range(max(iters, 3)):
        for k, run in variants.items():
            t0 = time.perf_counter()
            run()
            best[k] = min(best[k], time.perf_counter() - t0)

    out = {
        "scenario": "resnet50_injit_reduce",
        "num_grads": len(shapes),
        "total_mb": round(total_bytes / (1 << 20), 1),
        "num_devices": n,
        "threshold_mb": threshold // (1 << 20),
        "num_buckets": len(plan),
        "variants": {},
    }
    for k in variants:
        row = {
            "time_s": best[k],
            "wire_mb": round(total_bytes * elem_bytes[k] / 4 / (1 << 20), 1),
            "collectives_per_step": len(shapes) if k == "per_leaf"
            else len(plan),
        }
        if k != "per_leaf":
            row["max_abs_err_vs_fp32"] = errs[k]
        out["variants"][k] = row
    pl, pk = best["per_leaf"], best["packed"]
    out["packed_speedup_vs_per_leaf"] = round(pl / pk, 2) if pk > 0 else None
    return out


def scaling_sweep_point(batch_per_device: int = 8, image_size: int = 32,
                        model_name: str = "resnet18",
                        num_iters: int = 3,
                        num_batches_per_iter: int = 5) -> dict:
    """One point of the compiled-plane scaling sweep: DP train step over
    every visible device (the bench.py data plane), returning throughput.
    The root script runs this under 1/2/4/8 virtual CPU devices and
    derives efficiency = T(n) / (n * T(1))."""
    import jax

    from .benchmark import _Rig

    rig = _Rig(batch_per_device, image_size, model_name, "sgd")
    r = rig.run_stage(num_warmup_batches=2,
                      num_batches_per_iter=num_batches_per_iter,
                      num_iters=num_iters)
    return {
        "num_devices": r.num_chips,
        "batch_per_device": r.batch_per_chip,
        "images_per_sec_total": r.images_per_sec_total,
        "images_per_sec_per_device": r.images_per_sec_per_chip,
        "platform": r.platform,
    }


def _gen_workload(num_requests: int, shared_prefix: int = 0):
    """The generation sweeps' shared fixture: the tiny fp32 bench
    transformer plus a deterministic mixed-length workload — a few long
    generations pinned among bursts of short ones (the shape that
    strands static batches), mixed prompt lengths including one past
    the prefill chunk. ``shared_prefix > 0`` prepends that many
    identical system-prompt tokens to every prompt (the
    :func:`prefix_sweep` agentic/chat shape). Returns
    ``(model, params, cfg, prompts, new_lens)``."""
    import jax
    import jax.numpy as jnp

    from .models.transformer import Transformer, TransformerConfig

    cfg = TransformerConfig(vocab_size=512, num_layers=4, d_model=128,
                            num_heads=4, head_dim=32, max_seq_len=128,
                            dtype=jnp.float32)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))
    rng = np.random.RandomState(0)
    system = rng.randint(0, cfg.vocab_size, (shared_prefix,)).tolist()
    new_lens = [(32, 4, 4, 4, 8, 4, 16, 4)[i % 8]
                for i in range(num_requests)]
    prompts = [system + rng.randint(0, cfg.vocab_size,
                                    (4 + (i * 5) % 20,)).tolist()
               for i in range(num_requests)]
    return model, params, cfg, prompts, new_lens


def generation_sweep(num_requests: int = 24, batch_slots: int = 8,
                     block_size: int = 8) -> dict:
    """Continuous batching vs static full-batch generation on a
    mixed-length prompt workload (ROADMAP item 1's acceptance pair).

    Both modes run the same paged forward over the same pool shapes —
    static through the raw-logits ``build_program``, continuous through
    the engine's on-device-sampling programs (whose greedy tokens are
    pinned bit-identical to host argmax) — and every program is warmed
    off-clock, so the measured gap is pure scheduling + memory policy,
    not kernel or compile-time differences:

    * **static** — the classic served-systems baseline: requests form
      batches of ``batch_slots`` in arrival order; each batch prefills,
      reserves KV for its longest possible sequence in *every* slot,
      and decodes until its longest request finishes — finished lanes
      keep burning decode steps, and the next batch cannot start early.
    * **continuous** — the :class:`GenerationEngine` end-to-end:
      iteration-level admission into freed slots, immediate retirement,
      paged allocate-on-growth.

    Reported per mode: wall seconds, useful tokens/sec (prompt tokens
    excluded), decode steps, and peak KV bytes (allocator high-water x
    block bytes for continuous; the reservation high-water for static).
    """
    import threading

    import jax.numpy as jnp

    from .models.transformer import PagedCache
    from .serving.generation import (GenerationEngine, block_bytes,
                                     build_program, make_pools)
    from .serving.generation.scheduler import DECODE_WIDTH
    from . import metrics as _metrics

    model, params, cfg, prompts, new_lens = _gen_workload(num_requests)
    prefill_chunk = 16
    total_new = sum(new_lens)
    per_block = block_bytes(cfg, block_size)
    program = build_program(model)
    max_blocks = -(-cfg.max_seq_len // block_size)

    # -- static full-batch baseline -----------------------------------------
    def run_static():
        peak_blocks = 0
        decode_steps = 0
        outs = {}
        t0 = time.perf_counter()
        for lo in range(0, num_requests, batch_slots):
            group = list(range(lo, min(lo + batch_slots, num_requests)))
            longest = max(len(prompts[i]) + new_lens[i] for i in group)
            per_seq = -(-longest // block_size)
            # static reservation: worst case for EVERY slot in the batch
            peak_blocks = max(peak_blocks, per_seq * len(group))
            # pool sized like the continuous engine's, so both modes
            # share the same compiled program shapes (the reservation
            # accounting above is what static *requires*, not what the
            # shared pool holds)
            k, v = make_pools(cfg, batch_slots * max_blocks + 1,
                              block_size)
            tables = np.zeros((batch_slots, max_blocks), np.int32)
            for j in range(len(group)):
                tables[j, :per_seq] = 1 + j * per_seq + np.arange(per_seq)
            seqs = [list(prompts[i]) for i in group]
            # prefill, one sequence at a time (the chunked program)
            for j, i in enumerate(group):
                done = 0
                while done < len(prompts[i]):
                    chunk = prompts[i][done:done + prefill_chunk]
                    buf = np.zeros((1, prefill_chunk), np.int32)
                    buf[0, :len(chunk)] = chunk
                    cache = PagedCache(k, v, jnp.asarray(tables[j:j + 1]),
                                       jnp.asarray([done], jnp.int32),
                                       jnp.asarray([len(chunk)], jnp.int32))
                    logits, cache = program(params, cache, jnp.asarray(buf))
                    k, v = cache.k, cache.v
                    done += len(chunk)
                seqs[j].append(int(np.argmax(
                    np.asarray(logits)[0, len(chunk) - 1])))
            # decode to the BATCH max — finished lanes keep stepping
            batch_max = max(new_lens[i] for i in group)
            for _step in range(batch_max - 1):
                tokens = np.zeros((batch_slots, DECODE_WIDTH), np.int32)
                lengths = np.zeros((batch_slots,), np.int32)
                live = np.zeros((batch_slots,), np.int32)
                for j in range(len(group)):
                    tokens[j, 0] = seqs[j][-1]
                    lengths[j] = len(seqs[j]) - 1
                    live[j] = 1
                cache = PagedCache(k, v, jnp.asarray(tables),
                                   jnp.asarray(lengths), jnp.asarray(live))
                logits, cache = program(params, cache, jnp.asarray(tokens))
                k, v = cache.k, cache.v
                decode_steps += 1
                for j in range(len(group)):
                    seqs[j].append(int(np.argmax(np.asarray(logits)[j, 0])))
            for j, i in enumerate(group):
                outs[i] = seqs[j][len(prompts[i]):
                                  len(prompts[i]) + new_lens[i]]
        wall = time.perf_counter() - t0
        return wall, peak_blocks, decode_steps, outs

    # -- continuous batching -------------------------------------------------
    def run_continuous():
        snap0 = _metrics.snapshot()
        engine = GenerationEngine(
            model, params=params, block_size=block_size,
            num_blocks=batch_slots * max_blocks + 1, max_seqs=batch_slots,
            prefill_chunk=prefill_chunk, queue_depth=num_requests,
            deadline_ms=0)
        outs = [None] * num_requests
        t0 = time.perf_counter()

        def client(i):
            outs[i] = engine.generate(prompts[i], max_tokens=new_lens[i],
                                      timeout=600)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(num_requests)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        snap1 = _metrics.snapshot()
        occ0 = snap0.get("hvd_tpu_gen_batch_occupancy",
                         {"count": 0, "sum": 0})
        occ1 = snap1["hvd_tpu_gen_batch_occupancy"]
        steps = int(occ1["count"] - occ0["count"])
        occupancy = (occ1["sum"] - occ0["sum"]) / max(1, steps)
        preempt = snap1.get("hvd_tpu_gen_preemptions_total", 0) \
            - snap0.get("hvd_tpu_gen_preemptions_total", 0)
        peak = engine.allocator.peak_in_use
        leaked = engine.allocator.in_use
        engine.close()
        assert leaked == 0, f"{leaked} KV blocks leaked"
        return wall, peak, steps, occupancy, preempt, outs

    # compile every program before any clock starts: the static baseline
    # uses the raw-logits build_program shapes, the engine the sampled
    # prefill/decode programs — warm both modes off-clock
    run_static()
    run_continuous()
    st_wall, st_peak, st_steps, st_outs = run_static()
    ct_wall, ct_peak, ct_steps, ct_occ, ct_preempt, ct_outs = \
        run_continuous()
    # same greedy tokens from both schedulers, or the comparison is moot
    mismatch = sum(st_outs[i] != ct_outs[i] for i in range(num_requests))
    assert mismatch == 0, f"{mismatch} sequences diverged across modes"

    return {
        "scenario": "mixed_length_generation",
        "num_requests": num_requests,
        "batch_slots": batch_slots,
        "block_size": block_size,
        "num_blocks": batch_slots * max_blocks + 1,
        "model": {"layers": cfg.num_layers, "d_model": cfg.d_model,
                  "heads": cfg.num_heads, "head_dim": cfg.head_dim,
                  "vocab": cfg.vocab_size, "max_seq_len": cfg.max_seq_len},
        "total_prompt_tokens": sum(len(p) for p in prompts),
        "total_new_tokens": total_new,
        "static": {
            "wall_s": round(st_wall, 3),
            "tokens_per_s": round(total_new / st_wall, 1),
            "decode_steps": st_steps,
            "peak_kv_blocks": st_peak,
            "peak_kv_bytes": st_peak * per_block,
        },
        "continuous": {
            "wall_s": round(ct_wall, 3),
            "tokens_per_s": round(total_new / ct_wall, 1),
            "decode_steps": ct_steps,
            "avg_occupancy": round(ct_occ, 2),
            "preemptions": int(ct_preempt),
            "peak_kv_blocks": ct_peak,
            "peak_kv_bytes": ct_peak * per_block,
        },
        "continuous_speedup": round(st_wall / ct_wall, 2),
        "kv_bytes_vs_static_reservation": round(ct_peak / st_peak, 3)
        if st_peak else None,
    }


def sampling_sweep(num_requests: int = 16, batch_slots: int = 8,
                   block_size: int = 8) -> dict:
    """On-device sampling modes under sync vs async stepping (ISSUE 11).

    Same tiny model and mixed-length workload class as
    :func:`generation_sweep`, driven through the
    :class:`GenerationEngine` in four modes: ``greedy`` vs ``sampled``
    (temperature + top-k + top-p, seeded per request), each at
    ``async_depth`` 0 (synchronous) and 1 (double-buffered). Reported
    per mode: wall seconds, useful tokens/sec, and the host/device
    milliseconds per scheduler iteration read from the
    ``hvd_tpu_gen_step_seconds{component}`` histogram deltas — the
    before/after for the ROADMAP's live-TPU host-overhead re-measure.
    Each sampling mode's outputs are asserted identical across depths
    (depth-1 reconciliation must not change a single token).
    """
    import threading

    from .serving.generation import GenerationEngine
    from . import metrics as _metrics

    model, params, cfg, prompts, new_lens = _gen_workload(num_requests)
    total_new = sum(new_lens)
    max_blocks = -(-cfg.max_seq_len // block_size)
    sampled_kw = dict(temperature=0.9, top_k=32, top_p=0.9)

    def run_mode(sampled: bool, async_depth: int):
        snap0 = _metrics.snapshot()
        engine = GenerationEngine(
            model, params=params, block_size=block_size,
            num_blocks=batch_slots * max_blocks + 1, max_seqs=batch_slots,
            prefill_chunk=16, queue_depth=num_requests, deadline_ms=0,
            async_depth=async_depth)
        outs = [None] * num_requests
        t0 = time.perf_counter()

        def client(i):
            kw = dict(sampled_kw, seed=1000 + i) if sampled else {}
            outs[i] = engine.generate(prompts[i], max_tokens=new_lens[i],
                                      timeout=600, **kw)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(num_requests)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        snap1 = _metrics.snapshot()
        leaked = engine.allocator.in_use
        engine.close()
        assert leaked == 0, f"{leaked} KV blocks leaked"
        split = {}
        for comp in ("host", "device"):
            key = f'hvd_tpu_gen_step_seconds{{component="{comp}"}}'
            h0 = snap0.get(key, {"sum": 0.0, "count": 0})
            h1 = snap1.get(key, {"sum": 0.0, "count": 0})
            iters = h1["count"] - h0["count"]
            split[comp] = (h1["sum"] - h0["sum"]) / max(1, iters)
            split["iters"] = int(iters)
        return {
            "wall_s": round(wall, 3),
            "tokens_per_s": round(total_new / wall, 1),
            "scheduler_iters": split["iters"],
            "host_ms_per_step": round(split["host"] * 1e3, 3),
            "device_ms_per_step": round(split["device"] * 1e3, 3),
        }, outs

    modes = {}
    outputs = {}
    # compile both programs (and warm the jit caches) off the clock
    run_mode(sampled=False, async_depth=0)
    run_mode(sampled=True, async_depth=0)
    for name, sampled, depth in (("greedy_sync", False, 0),
                                 ("greedy_async1", False, 1),
                                 ("sampled_sync", True, 0),
                                 ("sampled_async1", True, 1)):
        modes[name], outputs[name] = run_mode(sampled, depth)
    # depth-1 reconciliation must be invisible in the outputs
    assert outputs["greedy_sync"] == outputs["greedy_async1"], \
        "greedy outputs diverged between sync and async stepping"
    assert outputs["sampled_sync"] == outputs["sampled_async1"], \
        "seeded sampled outputs diverged between sync and async stepping"

    return {
        "scenario": "on_device_sampling",
        "num_requests": num_requests,
        "batch_slots": batch_slots,
        "block_size": block_size,
        "num_blocks": batch_slots * max_blocks + 1,
        "total_new_tokens": total_new,
        "sampled_params": sampled_kw,
        "modes": modes,
        "async_speedup_greedy": round(
            modes["greedy_sync"]["wall_s"]
            / modes["greedy_async1"]["wall_s"], 2),
        "async_speedup_sampled": round(
            modes["sampled_sync"]["wall_s"]
            / modes["sampled_async1"]["wall_s"], 2),
    }


def prefix_sweep(num_requests: int = 24, batch_slots: int = 8,
                 block_size: int = 16) -> dict:
    """Automatic prefix caching on a shared-system-prompt workload
    (ISSUE 12's acceptance pair).

    Every request is one 64-token shared system prompt plus a short
    private suffix — the chat/agentic serving shape. Two engine runs
    over the SAME compiled programs (the sampling prefill/decode
    programs are memoized on the model): ``cache_off`` prefills every
    prompt in full; ``cache_on`` serves request 0 alone to warm the
    index, then the concurrent burst attaches the system prompt's
    blocks (``hvd_tpu_gen_prefix_cache_hit_tokens_total``) and prefills
    only its private suffix. Request 0 runs first in BOTH modes so the
    schedules differ only in cache policy. Outputs are asserted
    bit-identical across modes and no KV block may leak; reported per
    mode: wall seconds, useful tokens/sec, prefilled tokens (the
    ``hvd_tpu_gen_tokens_total{phase="prefill"}`` delta), and the
    prefix-cache hit/miss/eviction counters.
    """
    import threading

    from .serving.generation import GenerationEngine
    from . import metrics as _metrics

    system_tokens = 64
    model, params, cfg, prompts, new_lens = _gen_workload(
        num_requests, shared_prefix=system_tokens)
    total_new = sum(new_lens)
    max_blocks = -(-cfg.max_seq_len // block_size)
    num_blocks = batch_slots * max_blocks + 1

    def run(prefix_cache):
        snap0 = _metrics.snapshot()
        engine = GenerationEngine(
            model, params=params, block_size=block_size,
            num_blocks=num_blocks, max_seqs=batch_slots,
            prefill_chunk=16, queue_depth=num_requests, deadline_ms=0,
            prefix_cache=prefix_cache)
        outs = [None] * num_requests
        t0 = time.perf_counter()
        # request 0 runs alone first — with the cache on it warms the
        # index so every burst request below finds the system prompt
        outs[0] = engine.generate(prompts[0], max_tokens=new_lens[0],
                                  timeout=600)

        def client(i):
            outs[i] = engine.generate(prompts[i], max_tokens=new_lens[i],
                                      timeout=600)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(1, num_requests)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        snap1 = _metrics.snapshot()
        leaked = engine.allocator.in_use
        engine.close()
        assert leaked == 0, f"{leaked} KV blocks leaked"

        def delta(key):
            return snap1.get(key, 0) - snap0.get(key, 0)

        return {
            "wall_s": round(wall, 3),
            "tokens_per_s": round(total_new / wall, 1),
            "prefill_tokens": int(delta(
                'hvd_tpu_gen_tokens_total{phase="prefill"}')),
            "hit_tokens": int(delta(
                'hvd_tpu_gen_prefix_cache_hit_tokens_total'
                '{source="local"}')),
            "miss_tokens": int(delta(
                "hvd_tpu_gen_prefix_cache_miss_tokens_total")),
            "evictions": int(delta(
                "hvd_tpu_gen_prefix_cache_evictions_total")),
        }, outs

    # compile + warm both paths off the clock (fresh engine per run, so
    # no cache state crosses runs — only the jit caches are shared)
    run(prefix_cache=False)
    run(prefix_cache=True)
    cold, cold_outs = run(prefix_cache=False)
    warm, warm_outs = run(prefix_cache=True)
    mismatch = sum(cold_outs[i] != warm_outs[i]
                   for i in range(num_requests))
    assert mismatch == 0, f"{mismatch} sequences diverged across modes"

    return {
        "scenario": "shared_prefix_generation",
        "num_requests": num_requests,
        "batch_slots": batch_slots,
        "block_size": block_size,
        "num_blocks": num_blocks,
        "system_prompt_tokens": system_tokens,
        "total_prompt_tokens": sum(len(p) for p in prompts),
        "total_new_tokens": total_new,
        "cache_off": cold,
        "cache_on": warm,
        "cache_speedup": round(cold["wall_s"] / warm["wall_s"], 2),
        "prefill_reduction": round(
            1.0 - warm["prefill_tokens"] / cold["prefill_tokens"], 3),
    }


def spec_sweep(max_tokens: int = 96, spec_tokens: int = 4,
               block_size: int = 16, repeats: int = 3) -> dict:
    """N-gram speculative decoding vs plain decode (docs/inference.md),
    on the single-stream latency rig where speculation earns its keep.

    Speculative decoding is a latency play: one widened verify forward
    emits ``1 + accepted`` tokens, so the win scales with the accept
    rate and shows up where per-step cost, not batch throughput, is the
    bottleneck — the interactive single-sequence stream. The rig is a
    deeper bench transformer (8 x d256: enough compute per step that
    the verify chunk's cost is real, not dispatch noise) decoding one
    sequence at a time, spec off vs on over the same compiled prefill
    program, on two workloads:

    * **repetitive** — greedy decode. The model's continuation settles
      into a cycle, the prompt-lookup drafter replays it, and the
      accept rate climbs toward 1.0 — the structured-output /
      code-generation shape, speculation's best case.
    * **random** — seeded temperature/top-k/top-p sampling. The
      drafter's n-gram guesses almost never match a high-entropy
      sample, so speculation pays the wider forward for nothing — the
      honest worst case, reported rather than hidden.

    Outputs are asserted bit-identical across spec on/off for BOTH
    workloads (the correctness contract: speculation may only change
    speed) and no KV block may leak. Reported per mode: wall seconds
    per generation, tokens/sec, the n-gram accept rate
    (``hvd_tpu_gen_spec_accepted_total / ..._drafted_total``), and the
    verify-transfer ms/step from
    ``hvd_tpu_gen_step_seconds{component="verify"}``. The acceptance
    number is ``spec_speedup_repetitive`` (target >= 1.5x).
    """
    import jax
    import jax.numpy as jnp

    from .models.transformer import Transformer, TransformerConfig
    from .serving.generation import GenerationEngine
    from . import metrics as _metrics

    cfg = TransformerConfig(vocab_size=512, num_layers=8, d_model=256,
                            num_heads=4, head_dim=64, max_seq_len=256,
                            dtype=jnp.float32)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, cfg.vocab_size, (8,)).tolist()
    max_blocks = -(-cfg.max_seq_len // block_size)
    sampled_kw = dict(temperature=0.9, top_k=32, top_p=0.9, seed=1234)

    def run(spec_mode, sampled):
        engine = GenerationEngine(
            model, params=params, block_size=block_size,
            num_blocks=2 * max_blocks + 1, max_seqs=1, prefill_chunk=16,
            queue_depth=4, deadline_ms=0, spec_mode=spec_mode,
            spec_tokens=spec_tokens, max_beams=1)
        kw = dict(sampled_kw) if sampled else {}
        engine.generate(prompt, max_tokens=max_tokens, timeout=600, **kw)
        snap0 = _metrics.snapshot()
        t0 = time.perf_counter()
        for _ in range(repeats):
            out = engine.generate(prompt, max_tokens=max_tokens,
                                  timeout=600, **kw)
        wall = (time.perf_counter() - t0) / repeats
        snap1 = _metrics.snapshot()
        leaked = engine.allocator.in_use
        engine.close()
        assert leaked == 0, f"{leaked} KV blocks leaked"

        def delta(key):
            return snap1.get(key, 0) - snap0.get(key, 0)

        drafted = delta("hvd_tpu_gen_spec_drafted_total")
        accepted = delta("hvd_tpu_gen_spec_accepted_total")
        vkey = 'hvd_tpu_gen_step_seconds{component="verify"}'
        v0 = snap0.get(vkey, {"sum": 0.0, "count": 0})
        v1 = snap1.get(vkey, {"sum": 0.0, "count": 0})
        vsteps = v1["count"] - v0["count"]
        row = {
            "wall_s": round(wall, 3),
            "tokens_per_s": round(max_tokens / wall, 1),
        }
        if spec_mode != "off":
            row["drafted"] = int(drafted)
            row["accepted"] = int(accepted)
            row["accept_rate"] = round(accepted / max(1, drafted), 3)
            row["verify_steps"] = int(vsteps)
            row["verify_ms_per_step"] = round(
                (v1["sum"] - v0["sum"]) / max(1, vsteps) * 1e3, 3)
        return row, out

    modes = {}
    outputs = {}
    # compile the decode + verify programs off the clock
    run("off", sampled=False)
    run("ngram", sampled=False)
    for name, spec_mode, sampled in (
            ("repetitive_off", "off", False),
            ("repetitive_spec", "ngram", False),
            ("random_off", "off", True),
            ("random_spec", "ngram", True)):
        modes[name], outputs[name] = run(spec_mode, sampled)
    # speculation may only change speed — never a token or a logprob
    assert outputs["repetitive_off"] == outputs["repetitive_spec"], \
        "greedy outputs diverged between spec off and on"
    assert outputs["random_off"] == outputs["random_spec"], \
        "seeded sampled outputs diverged between spec off and on"

    return {
        "scenario": "speculative_decoding",
        "num_layers": cfg.num_layers,
        "d_model": cfg.d_model,
        "max_tokens": max_tokens,
        "spec_tokens": spec_tokens,
        "block_size": block_size,
        "sampled_params": {k: v for k, v in sampled_kw.items()
                           if k != "seed"},
        "modes": modes,
        "spec_speedup_repetitive": round(
            modes["repetitive_off"]["wall_s"]
            / modes["repetitive_spec"]["wall_s"], 2),
        "spec_speedup_random": round(
            modes["random_off"]["wall_s"]
            / modes["random_spec"]["wall_s"], 2),
        "bit_identical": True,
    }


def sdc_guard_sweep(steps: int = 40, rounds: int = 3,
                    fingerprint_every: int = 20) -> dict:
    """Overhead of the SDC defense plane (docs/robustness.md) on the
    ResNet-50 161-gradient scenario: a jit'd SGD update over the full
    gradient set, plain vs with :func:`sdc.guard_update` fused into the
    same program (the finite/magnitude checks and loss-spike bound ride
    the data the update is already streaming), plus the host-side
    parameter fingerprint fold amortized over ``fingerprint_every``
    steps. The guarded step only applies the update when the verdict is
    clean — exactly the Estimator integration — so the delta is the
    real per-step price of turning ``HVD_TPU_SDC_GUARD`` on."""
    import jax
    import jax.numpy as jnp

    from . import sdc

    shapes = resnet50_grad_shapes()
    rng = np.random.RandomState(0)
    params = [rng.randn(*s).astype(np.float32) * 0.01 for s in shapes]
    grads = [rng.randn(*s).astype(np.float32) * 0.001 for s in shapes]
    total_bytes = sum(p.nbytes for p in params)

    @jax.jit
    def step_plain(params, grads):
        return jax.tree_util.tree_map(
            lambda p, g: p - 0.01 * g, params, grads)

    @jax.jit
    def step_guarded(params, grads, loss, ewma):
        code, ewma = sdc.guard_update(grads, loss, ewma, factor=10.0)
        ok = code == 0
        new = jax.tree_util.tree_map(
            lambda p, g: jnp.where(ok, p - 0.01 * g, p), params, grads)
        return new, code, ewma

    def run_plain():
        ps = params
        for _ in range(steps):
            ps = step_plain(ps, grads)
        jax.block_until_ready(ps[-1])

    def run_guarded():
        ps, ewma = params, jnp.float32(1.0)
        for i in range(steps):
            ps, code, ewma = step_guarded(ps, grads, 1.0, ewma)
            if (i + 1) % fingerprint_every == 0:
                sdc.fold_fingerprint(ps)
        jax.block_until_ready(ps[-1])

    t0 = time.perf_counter()
    fp = sdc.fold_fingerprint(params)
    fingerprint_s = time.perf_counter() - t0
    assert 0 <= fp < 2 ** 32

    # interleaved A/B rounds, best-round estimates (see eager_sweep)
    run_plain(), run_guarded()
    t_plain = t_guard = float("inf")
    for _ in range(max(rounds, 2)):
        t0 = time.perf_counter()
        run_plain()
        t_plain = min(t_plain, time.perf_counter() - t0)
        t0 = time.perf_counter()
        run_guarded()
        t_guard = min(t_guard, time.perf_counter() - t0)

    plain_ms = t_plain / steps * 1e3
    guard_ms = t_guard / steps * 1e3
    return {
        "scenario": "resnet50_sdc_guard",
        # the <2% target assumes the guard's reductions fuse into the
        # update's data pass (accelerator XLA); CPU runs the extra
        # pass unfused, so interpret overhead_pct against platform
        "platform": jax.default_backend(),
        "num_grads": len(shapes),
        "total_mb": round(total_bytes / (1 << 20), 1),
        "steps_timed": steps,
        "fingerprint_every": fingerprint_every,
        "plain_ms_per_step": round(plain_ms, 3),
        "guarded_ms_per_step": round(guard_ms, 3),
        "fingerprint_fold_ms": round(fingerprint_s * 1e3, 3),
        "fingerprint_amortized_ms": round(
            fingerprint_s * 1e3 / fingerprint_every, 4),
        "overhead_pct": round((guard_ms - plain_ms) / plain_ms * 100, 2)
        if plain_ms > 0 else None,
        "target_pct": 2.0,
    }


def tracing_overhead_sweep(requests: int = 20000, rounds: int = 3) -> dict:
    """Per-request cost of the distributed tracer (docs/timeline.md) on
    the serving hot path's instrumentation sequence — one root
    ``request_span``, one nested span, one retroactive ``emit_span``,
    and one ``collective`` hook per request (the four call-site shapes
    the router/batcher/scheduler wiring added) — measured with
    ``HVD_TPU_TRACE_SAMPLE=0`` (the shipped default: every call site
    must reduce to the module-global no-op guard) and ``=1`` (every
    request traced into the in-memory ring; no span file). The ``off``
    delta over the bare loop is the acceptance number: tracing disabled
    must be within noise of not instrumenting at all."""
    import os

    from . import tracing

    rids = [f"{i:016x}" for i in range(requests)]
    entry = ("allreduce", "grad_0", (1024,), "float32")

    def run_bare():
        for _ in range(requests):
            t = time.monotonic()
            assert t

    def run_traced():
        for rid in rids:
            with tracing.request_span("server.generate", rid):
                with tracing.span("gen.prefill"):
                    tracing.collective(entry)
                t = time.monotonic()
                tracing.emit_span(tracing.current(), "gen.decode", t, t)

    def set_rate(rate):
        os.environ["HVD_TPU_TRACE_SAMPLE"] = rate
        tracing.reset()

    prior = os.environ.get("HVD_TPU_TRACE_SAMPLE")
    try:
        # interleaved A/B/C rounds, best-round estimates (eager_sweep)
        t_bare = t_off = t_on = float("inf")
        for _ in range(max(rounds, 2) + 1):  # first round doubles as warmup
            t0 = time.perf_counter()
            run_bare()
            t_bare = min(t_bare, time.perf_counter() - t0)
            set_rate("0")
            assert tracing.tracer() is None
            t0 = time.perf_counter()
            run_traced()
            t_off = min(t_off, time.perf_counter() - t0)
            set_rate("1")
            assert tracing.tracer() is not None
            t0 = time.perf_counter()
            run_traced()
            t_on = min(t_on, time.perf_counter() - t0)
    finally:
        if prior is None:
            os.environ.pop("HVD_TPU_TRACE_SAMPLE", None)
        else:
            os.environ["HVD_TPU_TRACE_SAMPLE"] = prior
        tracing.reset()

    bare_us = t_bare / requests * 1e6
    off_us = t_off / requests * 1e6
    on_us = t_on / requests * 1e6
    return {
        "scenario": "request_tracing_overhead",
        "requests_timed": requests,
        "call_sites_per_request": 4,
        "spans_per_request_on": 4,
        "bare_us_per_req": round(bare_us, 4),
        "off_us_per_req": round(off_us, 4),
        "on_us_per_req": round(on_us, 4),
        # what HVD_TPU_TRACE_SAMPLE=0 costs over no instrumentation
        "off_overhead_us_per_req": round(off_us - bare_us, 4),
        # what turning tracing ON costs over leaving it off
        "on_overhead_us_per_req": round(on_us - off_us, 4),
        "on_over_off": round(on_us / off_us, 2) if off_us > 0 else None,
    }


def hedging_sweep(requests: int = 80, slow_every: int = 10,
                  slow_ms: float = 250.0, fast_ms: float = 4.0,
                  hedge_quantile: float = 0.8) -> dict:
    """Tail latency of the fleet router's hedged retries
    (docs/robustness.md request survivability) under a workload where
    1-in-``slow_every`` requests stalls on its replica for ``slow_ms``
    — the canonical straggler shape hedging exists for. The replicas
    are latency-scripted HTTP stubs (no model): the quantity under
    test is the ROUTER's hedge race, not a forward pass. Reports
    p50/p99 with hedging off and on; the p99 ratio is the acceptance
    number — the slow tail collapses to roughly the hedge delay.

    The quantile sits BELOW the slow fraction (0.8 < 0.9): the router
    indexes its sorted latency window at ``int(q * n)``, so with
    exactly 10% slow a 0.9 quantile lands on the first slow sample and
    the hedge delay degenerates to the straggler latency itself. The
    retry budget is pinned wide open for the run — the budget's
    collapse-to-pass-through behaviour is a correctness property
    (tests/test_failover.py), not the tail effect measured here."""
    import json as _json
    import os
    import threading
    import urllib.request
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from .serving import fleet

    def make_stub():
        class _Stub(BaseHTTPRequestHandler):
            count = 0
            lock = threading.Lock()

            def do_GET(self):  # healthz for circuit probes
                self._answer(b'{"ok": true}')

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                self.rfile.read(length)
                if self.path != "/v1/cancel":
                    with type(self).lock:
                        type(self).count += 1
                        n = type(self).count
                    if n % slow_every == 0:
                        time.sleep(slow_ms / 1e3)
                    else:
                        time.sleep(fast_ms / 1e3)
                self._answer(b'{"outputs": []}')

            def _answer(self, body):
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        srv = ThreadingHTTPServer(("127.0.0.1", 0), _Stub)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv

    knobs = {"HVD_TPU_FLEET_HEDGE_QUANTILE": None,
             "HVD_TPU_FLEET_RETRY_BUDGET_RATIO": "1.0",
             "HVD_TPU_FLEET_RETRY_BUDGET_BURST": "64"}

    def measure(quantile):
        knobs["HVD_TPU_FLEET_HEDGE_QUANTILE"] = str(quantile)
        prior = {k: os.environ.get(k) for k in knobs}
        os.environ.update(knobs)
        stubs = [make_stub(), make_stub()]
        try:
            router = fleet.FleetRouter(
                {f"r{i}": f"http://127.0.0.1:{s.server_address[1]}"
                 for i, s in enumerate(stubs)},
                port=0, addr="127.0.0.1")
            router.start()
            lat = []
            body = _json.dumps({"inputs": [[0.0]]}).encode()
            for _ in range(requests):
                req = urllib.request.Request(
                    router.url + "/v1/infer", data=body, method="POST",
                    headers={"Content-Type": "application/json"})
                t0 = time.perf_counter()
                with urllib.request.urlopen(req, timeout=30) as resp:
                    resp.read()
                lat.append((time.perf_counter() - t0) * 1e3)
            router.stop()
            return lat
        finally:
            for k, v in prior.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            for s in stubs:
                s.shutdown()
                s.server_close()

    from . import metrics as _metrics
    off = measure(0.0)
    before = _metrics.snapshot()
    on = measure(hedge_quantile)
    snap = _metrics.snapshot()

    def pct(xs, q):
        return round(float(np.percentile(np.asarray(xs), q)), 2)

    launched = snap.get('hvd_tpu_fleet_hedges_total{outcome="launched"}',
                        0) - before.get(
        'hvd_tpu_fleet_hedges_total{outcome="launched"}', 0)
    won = snap.get('hvd_tpu_fleet_hedges_total{outcome="won"}',
                   0) - before.get(
        'hvd_tpu_fleet_hedges_total{outcome="won"}', 0)
    return {
        "scenario": "fleet_hedging_tail",
        "requests": requests,
        "slow_every": slow_every,
        "slow_ms": slow_ms,
        "fast_ms": fast_ms,
        "hedge_quantile": hedge_quantile,
        "off": {"p50_ms": pct(off, 50), "p99_ms": pct(off, 99)},
        "on": {"p50_ms": pct(on, 50), "p99_ms": pct(on, 99),
               "hedges_launched": int(launched), "hedges_won": int(won)},
        "p99_speedup": round(pct(off, 99) / max(pct(on, 99), 1e-9), 2),
    }


def resume_sweep(emitted: int = 256, prompt_len: int = 8,
                 block_size: int = 8) -> dict:
    """Cost of a mid-stream failover resume — re-submitting
    ``prompt + emitted`` with the journaled seed and ``sample_offset``
    — at ``emitted`` already-delivered tokens, with the automatic
    prefix cache on vs off (docs/inference.md). With the cache on, the
    original generation's blocks are still resident, so the resume's
    re-prefill is mostly block reuse; off, it recomputes every chunk.
    The time to the resumed FIRST token is what a live client observes
    as the failover gap."""
    import jax
    import jax.numpy as jnp

    from .models.transformer import Transformer, TransformerConfig
    from .serving.generation import GenerationEngine

    cfg = TransformerConfig(vocab_size=512, num_layers=4, d_model=128,
                            num_heads=4, head_dim=32,
                            max_seq_len=prompt_len + emitted + 8,
                            dtype=jnp.float32)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, cfg.vocab_size, (prompt_len,)).tolist()
    num_blocks = 2 * ((prompt_len + emitted + 8) // block_size + 2)
    sampling = dict(temperature=0.9, top_k=40, top_p=0.95, seed=7)

    def run(prefix_cache):
        with GenerationEngine(model, params=params,
                              block_size=block_size,
                              num_blocks=num_blocks, max_seqs=2,
                              prefill_chunk=32, deadline_ms=0,
                              prefix_cache=prefix_cache) as eng:
            head = eng.result(
                eng.submit(prompt, max_tokens=emitted, **sampling),
                timeout=1200)
            # the failover moment: re-submit prompt+emitted elsewhere
            t0 = time.perf_counter()
            tail = eng.result(
                eng.submit(prompt + head, max_tokens=1,
                           sample_offset=emitted, **sampling),
                timeout=1200)
            first_token_ms = (time.perf_counter() - t0) * 1e3
            return head, tail, round(first_token_ms, 2)

    head_on, tail_on, ms_on = run(True)
    head_off, tail_off, ms_off = run(False)
    return {
        "scenario": "stream_resume_cost",
        "emitted_tokens": emitted,
        "prompt_len": prompt_len,
        # same seed + sample_offset: both engines must continue the
        # same sampled stream (the bit-identity the failover relies on)
        "bit_identical": bool(head_on == head_off
                              and tail_on == tail_off),
        "resume_first_token_ms_cache_on": ms_on,
        "resume_first_token_ms_cache_off": ms_off,
        "cached_resume_speedup": round(ms_off / max(ms_on, 1e-9), 2),
    }


def disagg_sweep(num_requests: int = 16, batch_slots: int = 8,
                 block_size: int = 16) -> dict:
    """Disaggregated prefill/decode serving vs colocated (ISSUE 19's
    acceptance pair), end to end through real HTTP fleets.

    The same mixed long-prefill/long-decode workload (the
    :func:`prefix_sweep` shared-64-token-system-prompt shape, whose
    long prompts are exactly what stalls colocated decodes) runs twice
    over the same compiled programs:

    * **colocated** — two ``role='colocated'`` replicas behind a plain
      :class:`FleetRouter` (the PR 13 fleet, least-outstanding).
    * **pooled** — one prefill replica + one decode replica behind a
      pooled router: every request prestages on the prefill pool, the
      KV manifest is offered to the decode replica, and only missing
      blocks move (``hvd_tpu_disagg_transfer_bytes_total``).

    Outputs are asserted bit-identical across modes (the disagg
    correctness contract), and a fully-warm repeat request through the
    pooled fleet is asserted to move ZERO transfer bytes — the
    content-addressed dedup acceptance number. Reported per mode: wall
    seconds, useful tokens/sec, and per-request latency p50/p99; the
    pooled row adds transfer bytes/seconds and the
    ``source="transfer"`` prefix-hit tokens."""
    import json as _json
    import threading
    import urllib.request

    from . import metrics as _metrics
    from .serving import InferenceServer
    from .serving import fleet
    from .serving.generation import GenerationEngine

    system_tokens = 64
    model, params, cfg, prompts, new_lens = _gen_workload(
        num_requests, shared_prefix=system_tokens)
    total_new = sum(new_lens)
    max_blocks = -(-cfg.max_seq_len // block_size)
    num_blocks = batch_slots * max_blocks + 1

    def make_replica(role):
        eng = GenerationEngine(
            model, params=params, block_size=block_size,
            num_blocks=num_blocks, max_seqs=batch_slots,
            prefill_chunk=16, queue_depth=num_requests, deadline_ms=0,
            role=role)
        srv = InferenceServer(None, port=0, addr="127.0.0.1",
                              gen_engine=eng)
        srv.start()
        return srv

    def post(url, doc):
        req = urllib.request.Request(
            url, data=_json.dumps(doc).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=600) as resp:
            return _json.loads(resp.read())

    TB = "hvd_tpu_disagg_transfer_bytes_total"
    TS = "hvd_tpu_disagg_transfer_seconds"
    HIT_T = ('hvd_tpu_gen_prefix_cache_hit_tokens_total'
             '{source="transfer"}')

    def run(pooled):
        if pooled:
            srvs = {"p0": make_replica("prefill"),
                    "d0": make_replica("decode")}
            pools = {"p0": "prefill", "d0": "decode"}
        else:
            srvs = {"r0": make_replica("colocated"),
                    "r1": make_replica("colocated")}
            pools = None
        router = fleet.FleetRouter(
            {rid: f"http://127.0.0.1:{s.port}"
             for rid, s in srvs.items()},
            port=0, addr="127.0.0.1", pools=pools)
        router.start()
        outs = [None] * num_requests
        lat = [0.0] * num_requests
        try:
            snap0 = _metrics.snapshot()
            t0 = time.perf_counter()

            def client(i):
                t1 = time.perf_counter()
                outs[i] = post(router.url + "/v1/generate",
                               {"prompt": prompts[i],
                                "max_tokens": new_lens[i]})["tokens"]
                lat[i] = (time.perf_counter() - t1) * 1e3
            # request 0 runs alone first — in the pooled fleet its cold
            # transfer ships the shared system prompt once, so the
            # burst's offers dedup against it
            client(0)
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(1, num_requests)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            snap1 = _metrics.snapshot()

            # fully-warm repeat: every manifest block of prompt 0 is
            # already indexed on the serving replica — the pooled hop
            # must move ZERO bytes (content-addressed dedup)
            repeat = post(router.url + "/v1/generate",
                          {"prompt": prompts[0],
                           "max_tokens": new_lens[0]})["tokens"]
            snap2 = _metrics.snapshot()
            assert repeat == outs[0], "warm repeat diverged"
            warm_bytes = snap2.get(TB, 0) - snap1.get(TB, 0)
            if pooled:
                assert warm_bytes == 0, \
                    f"warm shared prefix moved {warm_bytes} bytes"
        finally:
            router.stop()
            for s in srvs.values():
                s.close()

        def delta(key):
            return snap1.get(key, 0) - snap0.get(key, 0)

        lat_np = np.asarray(lat)
        row = {
            "wall_s": round(wall, 3),
            "tokens_per_s": round(total_new / wall, 1),
            "p50_ms": round(float(np.percentile(lat_np, 50)), 2),
            "p99_ms": round(float(np.percentile(lat_np, 99)), 2),
        }
        if pooled:
            row["transfer_bytes"] = int(delta(TB))
            row["transfer_seconds"] = round(delta(TS), 4)
            row["transfer_hit_tokens"] = int(delta(HIT_T))
            row["warm_repeat_transfer_bytes"] = int(warm_bytes)
        return row, outs

    # compile + warm both paths off the clock (fresh replicas per run;
    # only the jit caches are shared across runs)
    run(pooled=False)
    run(pooled=True)
    colo, colo_outs = run(pooled=False)
    pool, pool_outs = run(pooled=True)
    mismatch = sum(colo_outs[i] != pool_outs[i]
                   for i in range(num_requests))
    assert mismatch == 0, f"{mismatch} sequences diverged across modes"

    return {
        "scenario": "disagg_prefill_decode",
        "num_requests": num_requests,
        "batch_slots": batch_slots,
        "block_size": block_size,
        "num_blocks": num_blocks,
        "system_prompt_tokens": system_tokens,
        "total_new_tokens": total_new,
        "bit_identical": True,
        "colocated": colo,
        "pooled": pool,
    }
