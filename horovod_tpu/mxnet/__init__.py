"""MXNet interop (import-gated).

Reference surface: horovod/mxnet (/root/reference/horovod/mxnet/
__init__.py:37-107 — DistributedOptimizer allreducing in ``update``, gluon
DistributedTrainer, broadcast_parameters). MXNet is not part of this
image's stack (it reached end-of-life upstream); the module gates with a
clear error, and the collective plane it would bridge to is the same eager
host plane used by :mod:`horovod_tpu.torch` — an NDArray bridge
(asnumpy()/from numpy) is all an MXNet install would need, mirroring the
torch module's design.

Executed (not just imported) by ``tests/test_mxnet_stub.py``, which drives
every entry point through a stub ``mxnet`` module exposing the exact
NDArray/Trainer surface used here.
"""

from typing import Optional

from ..basics import (  # noqa: F401
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
)


def _require_mxnet():
    try:
        import mxnet  # noqa: F401
        return mxnet
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.mxnet requires mxnet, which is not installed in "
            "this environment (MXNet is end-of-life upstream). Use the "
            "jax/flax path (horovod_tpu), horovod_tpu.torch, or "
            "horovod_tpu.tensorflow instead."
        ) from e


def _to_mx(out, like):
    import numpy as np
    mx = _require_mxnet()
    return mx.nd.array(np.asarray(out), dtype=like.dtype)


def allreduce(tensor, average: bool = True, name: Optional[str] = None):
    _require_mxnet()
    from .. import collectives as _c
    out = _c.allreduce(tensor.asnumpy(), average=average, name=name)
    return _to_mx(out, tensor)


def grouped_allreduce(tensors, average: bool = True,
                      name: Optional[str] = None):
    """Fused allreduce of several NDArrays (reference:
    mxnet/mpi_ops.py grouped_allreduce)."""
    _require_mxnet()
    from .. import collectives as _c
    outs = _c.grouped_allreduce([t.asnumpy() for t in tensors],
                                average=average, name=name)
    return [_to_mx(o, t) for o, t in zip(outs, tensors)]


def allgather(tensor, name: Optional[str] = None):
    """Concatenate every process's tensor along dim 0 (reference:
    mxnet/mpi_ops.py:84-107 allgather)."""
    _require_mxnet()
    from .. import collectives as _c
    out = _c.allgather(tensor.asnumpy(), name=name)
    return _to_mx(out, tensor)


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None):
    _require_mxnet()
    from .. import collectives as _c
    out = _c.broadcast(tensor.asnumpy(), root_rank=root_rank, name=name)
    return _to_mx(out, tensor)


def alltoall(tensor, splits=None, name: Optional[str] = None):
    _require_mxnet()
    from .. import collectives as _c
    out = _c.alltoall(tensor.asnumpy(), splits=splits, name=name)
    return _to_mx(out, tensor)


def broadcast_object(obj, root_rank: int = 0, name: Optional[str] = None):
    from ..functions import broadcast_object as _bo
    return _bo(obj, root_rank=root_rank, name=name)


def broadcast_parameters(params, root_rank: int = 0):
    mx = _require_mxnet()
    import numpy as np
    from .. import collectives as _c
    items = sorted(params.items()) if isinstance(params, dict) \
        else sorted(dict(params).items())
    for name, p in items:
        arr = p.data() if hasattr(p, "data") else p
        out = _c.broadcast(arr.asnumpy(), root_rank=root_rank,
                           name=f"mx.bcast.{name}")
        arr[:] = mx.nd.array(np.asarray(out), dtype=arr.dtype)


def DistributedOptimizer(optimizer):
    """Wrap an mxnet optimizer so ``update`` allreduces gradients first
    (reference: mxnet/__init__.py:37-76)."""
    _require_mxnet()

    class _Dist(type(optimizer)):
        def update(self, index, weight, grad, state):
            reduced = allreduce(grad, average=True,
                                name=f"mx.grad.{index}")
            super().update(index, weight, reduced, state)

    optimizer.__class__ = _Dist
    return optimizer


def DistributedTrainer(params, optimizer, optimizer_params=None,
                       compression=None, gradient_predivide_factor: float = 1.0):
    """gluon Trainer whose ``_allreduce_grads`` reduces through the XLA
    collective plane (reference: mxnet/__init__.py:84-107
    DistributedTrainer: rescale_grad divided by world size, per-parameter
    allreduce of live grads; here consecutive ready grads fuse through
    grouped_allreduce).

    Returns an *instance* (the class is built lazily so the module imports
    without mxnet installed).
    """
    mx = _require_mxnet()
    from .. import basics as _basics

    if gradient_predivide_factor <= 0:
        raise ValueError(
            f"gradient_predivide_factor must be positive, got "
            f"{gradient_predivide_factor}")

    class _DistributedTrainer(mx.gluon.Trainer):
        def __init__(self, params_, optimizer_, optimizer_params_):
            if type(optimizer_).__name__ == "_Dist":
                raise ValueError(
                    "pass a plain optimizer (or its name) to "
                    "DistributedTrainer; it applies the distributed "
                    "reduction itself (reference mxnet/__init__.py:90)")
            super().__init__(params_, optimizer_,
                             optimizer_params_, kvstore=None)
            # the reference divides rescale_grad by size so the allreduce
            # SUM yields the average (mxnet/__init__.py:95-99). The
            # predivide factor must stay numerically NEUTRAL overall: it
            # moves part of the divide before the summation (overflow
            # control on narrow dtypes), so the allreduce carries
            # prescale=1/f and postscale=f — dividing _scale by f here
            # without the postscale would shrink effective gradients by
            # 1/f (the torch bridge's prescale/postscale contract).
            self._scale /= _basics.size()
            self._hvd_predivide = gradient_predivide_factor

        def _allreduce_grads(self):
            import numpy as np
            from .. import collectives as _c
            f = self._hvd_predivide
            live = [(i, p) for i, p in enumerate(self._params)
                    if p.grad_req != "null"]
            if not live:
                return
            grads = [p.list_grad()[0] for _, p in live]
            if compression is not None:
                pairs = [compression.compress(g.asnumpy()) for g in grads]
                outs = _c.grouped_allreduce(
                    [c for c, _ in pairs], average=False,
                    prescale_factor=1.0 / f, postscale_factor=f,
                    name="mx.trainer.grads")
                outs = [compression.decompress(o, ctx)
                        for o, (_, ctx) in zip(outs, pairs)]
            else:
                outs = _c.grouped_allreduce(
                    [g.asnumpy() for g in grads], average=False,
                    prescale_factor=1.0 / f, postscale_factor=f,
                    name="mx.trainer.grads")
            for (i, p), out in zip(live, outs):
                p.list_grad()[0][:] = mx.nd.array(
                    np.asarray(out), dtype=p.list_grad()[0].dtype)

    return _DistributedTrainer(params, optimizer, optimizer_params)
