"""MXNet interop (import-gated).

Reference surface: horovod/mxnet (/root/reference/horovod/mxnet/
__init__.py:37-107 — DistributedOptimizer allreducing in ``update``, gluon
DistributedTrainer, broadcast_parameters). MXNet is not part of this
image's stack (it reached end-of-life upstream); the module gates with a
clear error, and the collective plane it would bridge to is the same eager
host plane used by :mod:`horovod_tpu.torch` — an NDArray bridge
(asnumpy()/from numpy) is all an MXNet install would need, mirroring the
torch module's design.
"""

from typing import Optional

from ..basics import (  # noqa: F401
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
)


def _require_mxnet():
    try:
        import mxnet  # noqa: F401
        return mxnet
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.mxnet requires mxnet, which is not installed in "
            "this environment (MXNet is end-of-life upstream). Use the "
            "jax/flax path (horovod_tpu), horovod_tpu.torch, or "
            "horovod_tpu.tensorflow instead."
        ) from e


def allreduce(tensor, average: bool = True, name: Optional[str] = None):
    mx = _require_mxnet()
    from .. import collectives as _c
    out = _c.allreduce(tensor.asnumpy(), average=average, name=name)
    import numpy as np
    return mx.nd.array(np.asarray(out), dtype=tensor.dtype)


def broadcast_parameters(params, root_rank: int = 0):
    mx = _require_mxnet()
    import numpy as np
    from .. import collectives as _c
    items = sorted(params.items()) if isinstance(params, dict) \
        else sorted(dict(params).items())
    for name, p in items:
        arr = p.data() if hasattr(p, "data") else p
        out = _c.broadcast(arr.asnumpy(), root_rank=root_rank,
                           name=f"mx.bcast.{name}")
        arr[:] = mx.nd.array(np.asarray(out), dtype=arr.dtype)


def DistributedOptimizer(optimizer):
    """Wrap an mxnet optimizer so ``update`` allreduces gradients first
    (reference: mxnet/__init__.py:37-76)."""
    _require_mxnet()

    class _Dist(type(optimizer)):
        def update(self, index, weight, grad, state):
            reduced = allreduce(grad, average=True,
                                name=f"mx.grad.{index}")
            super().update(index, weight, reduced, state)

    optimizer.__class__ = _Dist
    return optimizer
