"""Opt-in runtime lock-order sentinel.

The static ``lock-order`` checker (``tools/analyze``) proves the
*declared* acquisition graph acyclic, but it cannot see dynamic dispatch
(callbacks, metric cells, handler threads). This module closes that gap
at runtime: with ``HVD_TPU_LOCK_CHECK=1`` every lock created through
:func:`lock` is wrapped in a :class:`_CheckedLock` that

* records, per thread, the stack of checked locks currently held;
* on each acquisition of ``B`` while holding ``A``, registers the
  global ordering edge ``A -> B`` (keyed by lock *name*, so every
  instance of a class contributes to one discipline);
* raises :class:`LockOrderError` **before blocking** when the reverse
  edge ``B -> A`` was ever observed anywhere in the process — the
  interleaving that, under the right timing, is a deadlock;
* raises :class:`LockOrderError` when a thread re-acquires the exact
  lock instance it already holds (a guaranteed self-deadlock for a
  non-reentrant lock).

With the knob off (the default) :func:`lock` returns a plain
``threading.Lock`` — zero overhead, nothing recorded. The threaded
modules (serving batcher/engine, checkpoint manager, rendezvous store,
heartbeat, stall inspector, metrics registry) create their locks through
this factory, and ``tests/conftest.py`` turns the sentinel on for the
whole suite, so any ordering regression fails loudly in CI instead of
deadlocking a production job once a year. See docs/static_analysis.md.
"""

import threading
from typing import Dict, Optional, Tuple

__all__ = ["lock", "LockOrderError", "enabled", "reset", "order_edges"]


class LockOrderError(RuntimeError):
    """Two checked locks were acquired in both orders (potential
    deadlock), or a thread re-acquired a lock instance it already holds."""


#: enabled-state cache: None = not yet resolved from the knob registry
_ENABLED: Optional[bool] = None

#: held-lock stack per thread: list of (name, id(instance))
_HELD = threading.local()

#: observed ordering edges: (held_name, acquired_name) -> provenance
#: string recorded at first observation. Guarded by _GRAPH_LOCK (a plain
#: lock — the sentinel must not instrument itself).
_EDGES: Dict[Tuple[str, str], str] = {}
_GRAPH_LOCK = threading.Lock()


def enabled() -> bool:
    """Whether the sentinel is active (``HVD_TPU_LOCK_CHECK``)."""
    global _ENABLED
    if _ENABLED is None:
        from . import config as _config
        _ENABLED = bool(_config.Config().get(_config.LOCK_CHECK))
    return _ENABLED


def reset() -> None:
    """Drop every recorded edge and re-read the knob (tests only)."""
    global _ENABLED
    with _GRAPH_LOCK:
        _EDGES.clear()
    _ENABLED = None


def order_edges() -> Dict[Tuple[str, str], str]:
    """Snapshot of the observed acquisition-order graph (introspection)."""
    with _GRAPH_LOCK:
        return dict(_EDGES)


def _stack():
    stack = getattr(_HELD, "stack", None)
    if stack is None:
        stack = _HELD.stack = []
    return stack


class _CheckedLock:
    """A ``threading.Lock`` that reports into the ordering sentinel."""

    __slots__ = ("name", "_inner")

    def __init__(self, name: str):
        self.name = name
        self._inner = threading.Lock()

    def _check_and_record(self) -> None:
        stack = _stack()
        me = id(self)
        for held_name, held_id in stack:
            if held_id == me:
                raise LockOrderError(
                    f"thread {threading.current_thread().name!r} "
                    f"re-acquired lock {self.name!r} it already holds "
                    f"(self-deadlock on a non-reentrant lock)")
        held_names = {n for n, _ in stack if n != self.name}
        if not held_names:
            return
        with _GRAPH_LOCK:
            for held in held_names:
                rev = _EDGES.get((self.name, held))
                if rev is not None:
                    raise LockOrderError(
                        f"lock-order violation: thread "
                        f"{threading.current_thread().name!r} acquires "
                        f"{self.name!r} while holding {held!r}, but the "
                        f"opposite order was observed earlier ({rev}) — "
                        f"this interleaving can deadlock")
            prov = (f"{held_names!r} -> {self.name!r} on thread "
                    f"{threading.current_thread().name!r}")
            for held in held_names:
                _EDGES.setdefault((held, self.name), prov)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._check_and_record()
        got = self._inner.acquire(blocking, timeout)
        if got:
            _stack().append((self.name, id(self)))
        return got

    def release(self) -> None:
        self._inner.release()
        stack = _stack()
        me = id(self)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][1] == me:
                del stack[i]
                break

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<_CheckedLock {self.name!r} at {id(self):#x}>"


def lock(name: str):
    """A lock participating in the ordering sentinel when
    ``HVD_TPU_LOCK_CHECK`` is on; a plain ``threading.Lock`` otherwise.

    ``name`` identifies the lock's *role* (conventionally
    ``<module>.<Class>.<attr>``); every instance created under one name
    shares one ordering discipline.
    """
    if enabled():
        return _CheckedLock(name)
    return threading.Lock()
