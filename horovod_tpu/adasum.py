"""Adasum gradient combining in JAX.

The reference implements Adasum — a scale-invariant way to combine gradients
from independent workers — as a templated C++ vector-halving
distance-doubling (VHDD) allreduce with AVX/F16C SIMD paths
(/root/reference/horovod/common/ops/adasum/adasum.h:195-399). The pairwise
rule (adasum.h:385-396):

    a' = (1 - dot(a,b) / (2·‖a‖²)) · a  +  (1 - dot(a,b) / (2·‖b‖²)) · b

On TPU none of the hand-rolled SIMD or point-to-point scheduling is needed:
the rule is a handful of reductions and FMAs that XLA maps straight onto the
VPU/MXU, and the recursive-halving schedule becomes a log2(n)-level reduction
tree unrolled inside one jitted program (or psums over mesh axes for the
in-jit variant). Like the reference (util.py num_rank_is_power_2 check), the
world size must be a power of two.

Hierarchy (reference AdasumGpuAllreduceOp, ops/adasum_gpu_operations.cc:
ReduceScatter within node -> Adasum across nodes -> Allgather): the in-jit
variant :func:`adasum_grads` accepts an ``inner_axis`` whose contributions
are first plain-averaged (the "local ranks share a model replica" view),
then Adasum-combined over the outer axis.
"""

from typing import List, Optional, Sequence

import numpy as np


def _jnp():
    import jax.numpy as jnp
    return jnp


def _is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def adasum_pair(a, b, eps: Optional[float] = None):
    """Combine two same-shape gradient tensors with the Adasum rule.

    Reductions are taken over the whole tensor (the reference applies the
    rule per fused-buffer entry, adasum.h:338-399).
    """
    jnp = _jnp()
    acc = jnp.float32 if a.dtype in (jnp.bfloat16, jnp.float16) else a.dtype
    af = a.astype(acc)
    bf = b.astype(acc)
    dot = jnp.sum(af * bf)
    na = jnp.sum(af * af)
    nb = jnp.sum(bf * bf)
    ca = jnp.where(na > 0, 1.0 - dot / (2.0 * jnp.where(na > 0, na, 1.0)), 0.0)
    cb = jnp.where(nb > 0, 1.0 - dot / (2.0 * jnp.where(nb > 0, nb, 1.0)), 0.0)
    return (ca * af + cb * bf).astype(a.dtype)


def adasum_tree(stacked):
    """Adasum-combine ``stacked[i]`` over axis 0 (length must be a power of
    two) with an unrolled log2(n) reduction tree — the compiled-SPMD
    equivalent of the reference's VHDD schedule (adasum.h:195-337)."""
    n = stacked.shape[0]
    if not _is_pow2(n):
        raise ValueError(
            f"Adasum requires a power-of-two number of contributions, got {n}"
            " (reference: horovod/common/util.py num_rank_is_power_2).")
    level = [stacked[i] for i in range(n)]
    while len(level) > 1:
        level = [adasum_pair(level[2 * i], level[2 * i + 1])
                 for i in range(len(level) // 2)]
    return level[0]


def adasum_eager(world, values: List, wm, prescale_factor: float = 1.0,
                 postscale_factor: float = 1.0) -> List:
    """Eager-plane Adasum allreduce used by
    ``horovod_tpu.allreduce(op=Adasum)``: stacks each process's tensor as a
    row of a global array and runs :func:`adasum_tree` replicated. Prescale
    is applied to inputs before combining and postscale to the result
    (reference: ScaleBuffer before/after Adasum dispatch)."""
    import jax
    from .collectives import _get_program, _global_from_local, _local_result

    jnp = _jnp()
    nproc = wm.num_procs
    if nproc == 1:
        def scale1(v):
            v = jnp.asarray(np.asarray(v))
            s = prescale_factor * postscale_factor
            return v if s == 1.0 else (v * s).astype(v.dtype)
        return [scale1(v) for v in values]
    if not _is_pow2(nproc):
        raise ValueError(
            f"Adasum requires a power-of-two world size, got {nproc}.")

    sig = ("adasum", nproc, wm.cache_key, prescale_factor, postscale_factor,
           tuple((tuple(np.shape(v)), str(np.asarray(v).dtype))
                 for v in values))

    def build():
        def f(*stacked):
            out = []
            for s in stacked:
                if prescale_factor != 1.0:
                    s = (s * prescale_factor).astype(s.dtype)
                r = adasum_tree(s)
                if postscale_factor != 1.0:
                    r = (r * postscale_factor).astype(r.dtype)
                out.append(r)
            return tuple(out)
        return jax.jit(f, out_shardings=wm.replicated_sharding())
    fn = _get_program(world, sig, build)
    globals_ = [_global_from_local(wm, np.asarray(v)) for v in values]
    outs = fn(*globals_)
    if not isinstance(outs, tuple):
        outs = (outs,)
    return [_local_result(o) for o in outs]


def adasum_grads(grads, outer_axis: str, inner_axis: Optional[str] = None):
    """In-jit Adasum for compiled training steps (use inside shard_map).

    ``grads`` is a pytree of per-device gradients. Contributions along
    ``inner_axis`` (e.g. chips within a host/slice, the reference's
    intra-node NCCL ReduceScatter stage) are plain-averaged first; then each
    tensor is Adasum-combined across ``outer_axis`` via all_gather + local
    tree (identical on every device, so XLA computes it once per device with
    one collective).
    """
    import jax
    import jax.numpy as jnp

    def combine(g):
        if inner_axis is not None:
            g = jax.lax.pmean(g, inner_axis)
        stacked = jax.lax.all_gather(g, outer_axis, axis=0, tiled=False)
        return adasum_tree(stacked)

    return jax.tree_util.tree_map(combine, grads)
