"""Process/world lifecycle for horovod_tpu.

TPU-native analogue of the reference's ``HorovodBasics``
(/root/reference/horovod/common/basics.py:25-215) and the C init path
(`InitializeHorovodOnce`, common/operations.cc:611-657). Instead of spawning a
background C++ coordination thread and rendezvousing MPI/Gloo communicators,
``init()``:

1. connects the JAX distributed runtime (coordinator address from the
   launcher's env contract — the analogue of the Gloo HTTP rendezvous,
   gloo/gloo_context.cc:70-171) when running multi-process;
2. builds the eager-plane :class:`~horovod_tpu.mesh.WorldMesh`;
3. starts host-side services (timeline, stall inspector, async coordinator).

Rank semantics (documented departure from the reference): the reference runs
one process per GPU, so ``rank`` is both a process and a device. On TPU the
single-controller model runs one process per *host* and addresses devices
through meshes, so:

* ``rank()/size()`` are **process**-granular (what eager collectives reduce
  over);
* ``device_count()/local_device_count()`` are chip-granular;
* inside compiled code, per-device identity comes from
  ``jax.lax.axis_index(axis)`` over the training mesh.

For learning-rate scaling in data-parallel training use
``horovod_tpu.dp_size()`` (= devices on the data axis), the moral equivalent
of the reference's ``hvd.size()`` in its GPU-per-process world.
"""

import atexit
import os
import socket
import threading
from typing import Optional, Sequence

from . import config as _config
from . import metrics as _metrics
from .exceptions import NotInitializedError

_lock = threading.Lock()
_world: Optional["World"] = None

_M_INITS = _metrics.counter(
    "hvd_tpu_init_total",
    "hvd.init() completions (elastic resets re-init, so a climbing count "
    "on a long-lived process is a reset-rate signal).")
_M_SHUTDOWNS = _metrics.counter(
    "hvd_tpu_shutdown_total", "hvd.shutdown() completions.")
_M_WORLD_SIZE = _metrics.gauge(
    "hvd_tpu_world_size", "Process count of the current world.")


class World:
    """Singleton world state (reference: HorovodGlobalState,
    common/global_state.h:42-122)."""

    def __init__(self, cfg: _config.Config):
        self.config = cfg
        self.process_id = 0
        self.num_processes = 1
        self.coordinator_addr = ""
        self.world_mesh = None          # WorldMesh, built in init()
        self.controller = None          # set when multi-process
        self.coordinator = None         # async fusion coordinator (lazy)
        self.timeline = None
        self.stall_inspector = None
        self.parameter_manager = None
        self.metrics_server = None      # Prometheus endpoint (metrics.py)
        self.process_sets = {}
        self.joined = False
        self.shutdown_requested = False

    # -- queries -------------------------------------------------------------
    def rank(self) -> int:
        return self.process_id

    def size(self) -> int:
        return self.num_processes

    def local_rank(self) -> int:
        # One process per host in the TPU model; if a launcher packs several
        # processes per host it exports the reference env contract.
        v = self.config.get(_config.LOCAL_RANK)
        return v if v >= 0 else 0

    def local_size(self) -> int:
        v = self.config.get(_config.LOCAL_SIZE)
        return v if v >= 0 else 1

    def cross_rank(self) -> int:
        v = self.config.get(_config.CROSS_RANK)
        return v if v >= 0 else self.process_id

    def cross_size(self) -> int:
        v = self.config.get(_config.CROSS_SIZE)
        return v if v >= 0 else self.num_processes


def _jax():
    import jax
    return jax


def _identity_from_comm(comm, coordinator_address):
    """Derive (coordinator_address, size, rank) from an MPI communicator
    (reference: ``hvd.init(comm=...)`` / horovod_init_comm,
    common/basics.py:33-65 — rank identity and rendezvous both ride the
    caller's communicator instead of env vars).

    ``comm`` is duck-typed on the mpi4py surface (``Get_rank``,
    ``Get_size``, ``bcast``), so any communicator-shaped object works —
    including a subcommunicator, in which case THIS job's world is that
    subcomm (the reference's subset-communicator semantics). Rank 0 of
    ``comm`` binds the JAX coordinator and broadcasts its address over
    the communicator itself, so no launcher env contract is needed.
    """
    import socket

    rank, size = int(comm.Get_rank()), int(comm.Get_size())
    if size > 1 and coordinator_address is None:
        addr = None
        if rank == 0:
            with socket.socket() as s:
                s.bind(("0.0.0.0", 0))
                port = s.getsockname()[1]
            addr = f"{_routable_host()}:{port}"
        coordinator_address = comm.bcast(addr, root=0)
    return coordinator_address, size, rank


def _routable_host() -> str:
    """A host identity peers can actually dial. ``gethostname()`` alone is
    a trap on stock Debian/Ubuntu, where /etc/hosts maps the hostname to
    127.0.1.1 — remote ranks would connect to themselves and hang in
    jax.distributed init. Prefer the default-route interface IP (UDP
    connect performs no traffic); keep the hostname when it already
    resolves to a routable address (reference: the driver/task services
    resolve a usable NIC the same spirit, runner/driver_service.py)."""
    import socket

    host = socket.gethostname()
    try:
        resolved = socket.gethostbyname(host)
    except OSError:
        resolved = "127.0.0.1"
    if not resolved.startswith("127."):
        return host
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 53))
            ip = s.getsockname()[0]
        if ip and not ip.startswith("127."):
            return ip
    except OSError:
        pass
    return host


def init(process_sets: Optional[Sequence[Sequence[int]]] = None,
         comm=None,
         coordinator_address: Optional[str] = None,
         num_processes: Optional[int] = None,
         process_id: Optional[int] = None,
         config_overrides: Optional[dict] = None) -> None:
    """Initialize horovod_tpu.

    Single-process (the default on a TPU host, where all local chips are
    addressable without any rendezvous): no arguments needed. Multi-process
    (launched by ``horovodrun-tpu`` or manually): the coordinator address and
    process identity come from arguments or the env contract
    (HVD_TPU_COORDINATOR_ADDR / HVD_TPU_RANK / HVD_TPU_SIZE — same shape as
    the reference's HOROVOD_GLOO_RENDEZVOUS_ADDR / HOROVOD_RANK / HOROVOD_SIZE
    contract, gloo/gloo_context.cc:142-165).

    ``comm``: an mpi4py communicator (or any object with
    ``Get_rank/Get_size/bcast``) supplying identity AND rendezvous — the
    reference's ``hvd.init(comm=...)`` (common/basics.py:33-65). A
    subcommunicator makes this job's world exactly that subcomm. A LIST of
    world ranks is the reference's other accepted form: it is turned into
    an mpi4py subcommunicator of ``COMM_WORLD`` (requires mpi4py; only the
    listed ranks may call ``init``).

    ``process_sets``: optional list of process-index lists, the analogue of
    the reference's subset communicators. Retrieve with
    :func:`process_set_mesh`.
    """
    global _world
    with _lock:
        if _world is not None:
            return
        cfg = _config.Config(config_overrides)
        w = World(cfg)

        # Fail fast on a malformed HVD_TPU_FAULT_SPEC: parsed here (once
        # per process) so a typo is a startup FaultSpecError, not a
        # mid-training surprise the elastic loop would retry forever.
        from . import faults as _faults
        _faults.ensure_configured()

        if comm is not None and isinstance(comm, (list, tuple)):
            try:
                from mpi4py import MPI
            except ImportError as e:
                raise ValueError(
                    "init(comm=[ranks]) requires mpi4py to split "
                    "COMM_WORLD; pass an mpi4py (sub)communicator or use "
                    "process_sets instead") from e
            ranks = sorted(set(comm))
            # MPI_Comm_create_group is collective over the GROUP only and
            # is erroneous from a non-member (unlike MPI_Comm_create's
            # COMM_NULL contract), so membership must be checked first.
            if MPI.COMM_WORLD.Get_rank() not in ranks:
                raise ValueError(
                    f"this process (COMM_WORLD rank "
                    f"{MPI.COMM_WORLD.Get_rank()}) is not in "
                    f"init(comm={ranks}); only listed ranks may call init")
            comm = MPI.COMM_WORLD.Create_group(
                MPI.COMM_WORLD.group.Incl(ranks))

        if comm is not None:
            coordinator_address, num_processes, process_id = \
                _identity_from_comm(comm, coordinator_address)

        addr = coordinator_address or cfg.get(_config.COORDINATOR_ADDR) or None
        n = num_processes if num_processes is not None else cfg.get(_config.SIZE)
        pid = process_id if process_id is not None else cfg.get(_config.RANK)

        jax = _jax()
        if addr and n and n > 1:
            # Controlled failure-detection latency: under an elastic launch
            # a dead peer must surface quickly so the driver's recovery
            # path (respawn + state restore) wins over a stalled job; a
            # non-elastic job has no recovery path and keeps the tolerant
            # jax default instead.
            heartbeat = cfg.get(_config.HEARTBEAT_TIMEOUT_SECONDS)
            if heartbeat < 0:
                heartbeat = 10.0 if cfg.get(_config.ELASTIC) else 100.0
            # Multi-process eager collectives on the CPU backend need a
            # cross-process implementation; jax versions that default the
            # flag to "none" fail at the FIRST collective ("Multiprocess
            # computations aren't implemented on the CPU backend"), not
            # at init. Select gloo only when the flag is still at that
            # default, so an explicit user/env choice always wins.
            missing = object()
            try:
                current = jax.config.read(
                    "jax_cpu_collectives_implementation")
            except (AttributeError, KeyError):
                current = missing  # this jax has no such flag to select
            if current in (None, "none"):
                try:
                    jax.config.update(
                        "jax_cpu_collectives_implementation", "gloo")
                except Exception:
                    import logging
                    logging.getLogger("horovod_tpu").warning(
                        "could not select gloo CPU collectives; "
                        "multi-process CPU collectives may fail",
                        exc_info=True)
            kwargs = {
                "coordinator_address": addr,
                "num_processes": n,
                "process_id": pid,
                "initialization_timeout": int(
                    cfg.get(_config.INIT_TIMEOUT_SECONDS)),
                "heartbeat_timeout_seconds": int(heartbeat),
                "shutdown_timeout_seconds": int(
                    cfg.get(_config.SHUTDOWN_TIMEOUT_SECONDS)),
            }
            # the timeout kwargs arrived across jax releases; passing one
            # an older runtime doesn't know is a TypeError, so offer only
            # what this jax accepts (older versions fall back to their
            # built-in heartbeat/shutdown defaults)
            import inspect
            accepted = inspect.signature(
                jax.distributed.initialize).parameters
            if not any(p.kind is inspect.Parameter.VAR_KEYWORD
                       for p in accepted.values()):
                kwargs = {k: v for k, v in kwargs.items() if k in accepted}
            jax.distributed.initialize(**kwargs)
            w.coordinator_addr = addr
        w.process_id = jax.process_index()
        w.num_processes = jax.process_count()

        from .mesh import WorldMesh
        w.world_mesh = WorldMesh()

        if process_sets:
            for i, ranks in enumerate(process_sets):
                w.process_sets[i] = w.world_mesh.subset(list(ranks))

        from .logging_setup import configure as _configure_logging
        _configure_logging(cfg)
        # metrics gate + exposition endpoint come up before the other
        # host services so their own startup telemetry is captured
        w.metrics_server = _metrics.configure(w)
        _M_INITS.inc()
        _M_WORLD_SIZE.set(w.num_processes)
        from .timeline import maybe_start_timeline
        w.timeline = maybe_start_timeline(w)
        from .stall import StallInspector
        w.stall_inspector = StallInspector(w)
        from .parameter_manager import maybe_create as _maybe_autotune
        w.parameter_manager = _maybe_autotune(w)

        _world = w
        atexit.register(_shutdown_quietly)


def _shutdown_quietly():
    try:
        shutdown()
    except Exception:
        pass


def shutdown() -> None:
    """Tear down world state (reference: horovod_shutdown,
    operations.cc:690-700). Safe to call twice; after shutdown, init() may be
    called again (elastic reset does exactly this,
    reference torch/elastic.py:46-49)."""
    global _world
    with _lock:
        w = _world
        if w is None:
            return
        w.shutdown_requested = True
        d = getattr(w, "dispatcher", None)
        if d is not None:
            d.stop()
        if w.coordinator is not None:
            w.coordinator.stop()
        if w.timeline is not None:
            w.timeline.close()
        if w.stall_inspector is not None:
            w.stall_inspector.stop()
        # flush + drop the collective schedule ledger so an elastic
        # reset's next generation restarts at sequence 0 on every rank
        from . import _schedule
        _schedule.reset()
        _metrics.stop_http_server(w.metrics_server)
        w.metrics_server = None
        _M_SHUTDOWNS.inc()
        if w.coordinator_addr:
            try:
                _jax().distributed.shutdown()
            except Exception:
                pass
        _world = None


def world() -> World:
    if _world is None:
        raise NotInitializedError()
    return _world


def is_initialized() -> bool:
    return _world is not None


def rank() -> int:
    return world().rank()


def size() -> int:
    return world().size()


def local_rank() -> int:
    return world().local_rank()


def local_size() -> int:
    return world().local_size()


def cross_rank() -> int:
    return world().cross_rank()


def cross_size() -> int:
    return world().cross_size()


def device_count() -> int:
    world()
    return _jax().device_count()


def local_device_count() -> int:
    world()
    return _jax().local_device_count()


def dp_size() -> int:
    """Device-granular world size: the number the reference calls hvd.size()
    in its one-process-per-GPU model. Use for LR scaling of data-parallel
    compiled training."""
    world()
    return _jax().device_count()


def mapped_axis_sizes() -> dict:
    """``{axis_name: size}`` for every named mesh axis mapped over the
    *current trace* (shard_map/pmap scope). Empty when called eagerly or
    under plain jit with no mapped axis — the signal the in-jit
    collective fast path (collectives.py, docs/injit.md) keys on.

    The axis environment moved between jax releases, so resolution is a
    fallback chain: public ``jax.core.get_axis_env`` where it exists,
    the private ``jax._src.core`` equivalent otherwise, and finally
    ``unsafe_get_axis_names`` + per-axis ``axis_frame`` (which returns
    the frame's size) for very old trees.
    """
    jax = _jax()
    get_env = getattr(jax.core, "get_axis_env", None)
    if get_env is None:
        try:
            from jax._src import core as _src_core
            get_env = getattr(_src_core, "get_axis_env", None)
        except ImportError:  # pragma: no cover - jax always has _src.core
            get_env = None
    if get_env is not None:
        try:
            return dict(get_env().axis_sizes)
        except Exception:
            pass
    try:
        from jax._src.core import unsafe_get_axis_names
        names = list(unsafe_get_axis_names())
    except Exception as e:
        # No resolution path left on this jax. Returning {} here would
        # make the in-jit fast path lower every collective with size-1
        # (no-op) semantics — silently unreduced gradients. Fail loudly
        # instead: HVD_TPU_INJIT_FASTPATH=0 routes callers back to the
        # eager dispatcher until the axis-env resolution is re-taught.
        raise RuntimeError(
            "cannot introspect the jax axis environment on this jax "
            "version (get_axis_env / unsafe_get_axis_names both "
            "unavailable), so mapped axes are indistinguishable from "
            "plain jit. Set HVD_TPU_INJIT_FASTPATH=0 to use the eager "
            "dispatcher, or extend mapped_axis_sizes() for this jax "
            "(docs/injit.md).") from e
    out = {}
    for n in names:
        try:
            out[n] = int(jax.core.axis_frame(n))
        except Exception:
            out[n] = 1
    return out


def mapped_axes() -> "tuple":
    """Names of the mapped mesh axes in scope for the current trace,
    outermost first (empty eagerly / under unmapped jit)."""
    return tuple(mapped_axis_sizes())


def is_homogeneous() -> bool:
    """True when every process has the same number of local devices
    (reference: mpi_controller.cc:25-81 homogeneity check)."""
    w = world()
    jax = _jax()
    counts = {}
    for d in jax.devices():
        counts[d.process_index] = counts.get(d.process_index, 0) + 1
    return len(set(counts.values())) <= 1


def process_set_mesh(i: int):
    """The WorldMesh for process set ``i`` registered at init()."""
    return world().process_sets[i]


def hostname() -> str:
    w = world()
    return w.config.get(_config.HOSTNAME) or socket.gethostname()


# -- capability queries (reference: mpi_built/gloo_built/nccl_built/...,
#    basics.py:140-215). On TPU the data plane is always XLA. -----------------
def xla_built() -> bool:
    return True


def tpu_available() -> bool:
    try:
        return any(d.platform == "tpu" for d in _jax().devices())
    except Exception:
        return False


def mpi_built() -> bool:
    return False


def mpi_enabled() -> bool:
    return False


def gloo_built() -> bool:
    return False


def nccl_built() -> bool:
    return False


def ccl_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def cuda_built() -> bool:
    return False


def rocm_built() -> bool:
    return False


def mpi_threads_supported() -> bool:
    return False
