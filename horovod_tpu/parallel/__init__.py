"""Parallelism strategies for horovod_tpu.

The reference implements data parallelism only (SURVEY.md §2.3); on TPU the
framework supplies the full set as first-class, mesh-native components:

* **DP / FSDP / TP** — sharding annotations over mesh axes
  (:mod:`.mesh_utils`, :mod:`.sharding`), reduced by XLA.
* **Hierarchical DP** — reduce_scatter(ICI) → psum(DCN) → all_gather(ICI)
  (:mod:`.hierarchical`), the NCCLHierarchicalAllreduce shape
  (/root/reference/horovod/common/ops/nccl_operations.cc:178-372).
* **Context parallelism / ring attention** — K/V blocks rotate around the
  'sp' ring via ppermute with flash-style online softmax
  (:mod:`.ring_attention`).
* **Sequence parallelism (Ulysses)** — all_to_all that trades the sequence
  axis for the head axis (:mod:`.ulysses`).
* **Pipeline parallelism** — microbatch schedule over the 'pp' axis with
  collective-permute activation transfer (:mod:`.pipeline`).
* **Expert parallelism (MoE)** — top-k routing + all_to_all token dispatch
  over the 'ep' axis (:mod:`.moe`).
"""

from .mesh_utils import (MeshConfig, make_training_mesh,  # noqa: F401
                         TRANSFORMER_RULES, fsdp_sharded_leaves,
                         require_axes)
from .hierarchical import hierarchical_allreduce, hierarchical_pmean  # noqa: F401
from .ring_attention import (  # noqa: F401
    ring_attention, ring_attention_flash,
)
from .ulysses import ulysses_attention  # noqa: F401
from .pipeline import pipeline_apply  # noqa: F401
from .moe import MoEMlp, moe_mlp, route_top1  # noqa: F401
