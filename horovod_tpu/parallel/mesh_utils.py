"""Training-mesh construction.

The reference's GLOBAL/LOCAL/CROSS communicator triple
(/root/reference/horovod/common/common.h:111) generalizes on TPU to an
N-dimensional device mesh whose axis order encodes interconnect locality:
the **last** axes map to adjacent devices (ICI neighbors), the **first** axis
crosses slices (DCN). Collectives over trailing axes ride ICI; leading axes
ride DCN — so put tp/sp (latency-critical, per-layer) innermost and dp
(once-per-step gradient reduction) outermost, the standard scaling recipe.
"""

import dataclasses
from typing import Optional, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Sizes of each parallelism axis; -1 on dp means "absorb the rest"."""
    dp: int = -1      # data parallel (gradient allreduce, DCN-tolerant)
    fsdp: int = 1     # sharded params/optimizer (ZeRO-3 style)
    pp: int = 1       # pipeline stages
    ep: int = 1       # expert parallel
    sp: int = 1       # sequence/context parallel (ring attention)
    tp: int = 1       # tensor parallel (innermost, ICI-adjacent)


AXIS_ORDER = ("dp", "fsdp", "pp", "ep", "sp", "tp")


def make_training_mesh(config: MeshConfig = MeshConfig(),
                       devices=None):
    """Build a Mesh with axes ('dp','fsdp','pp','ep','sp','tp').

    Axes of size 1 are kept (harmless to XLA, simplifies downstream specs).
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    sizes = {a: getattr(config, a) for a in AXIS_ORDER}
    fixed = int(np.prod([s for a, s in sizes.items() if a != "dp" and s > 0]))
    if sizes["dp"] == -1:
        if n % fixed != 0:
            raise ValueError(
                f"{n} devices not divisible by non-dp axes product {fixed}")
        sizes["dp"] = n // fixed
    total = int(np.prod(list(sizes.values())))
    if total != n:
        raise ValueError(
            f"mesh sizes {sizes} use {total} devices but {n} are available")
    arr = np.array(devices).reshape([sizes[a] for a in AXIS_ORDER])
    return Mesh(arr, AXIS_ORDER)


# Logical-axis -> mesh-axis rules for the transformer in models/transformer.py
# (flax nn.with_logical_partitioning names). 'embed' stays replicated across
# tp (activations shard over it only in sequence-parallel regions); params
# additionally shard over fsdp on their largest axis.
TRANSFORMER_RULES: Tuple[Tuple[str, Optional[str]], ...] = (
    ("vocab", "tp"),
    ("heads", "tp"),
    ("mlp", "tp"),
    ("embed", "fsdp"),
    ("kv", None),
)


def require_axes(mesh, *axis_names: str):
    """Fail fast when an axis name is not on ``mesh``.

    The runtime counterpart of the ``mesh-axis`` lint
    (docs/static_analysis.md): the lint proves *literal* axis names
    resolve, this check covers names that arrive in variables. Without
    it a typo'd axis surfaces as an opaque trace-time NameError deep
    inside shard_map — or, worse, a mispaired collective.
    """
    declared = tuple(mesh.axis_names)
    missing = [a for a in axis_names if a and a not in declared]
    if missing:
        raise ValueError(
            f"axis name(s) {missing} not on this mesh (declared axes, "
            f"outermost first: {declared}); pipeline/MoE stages must "
            f"agree on the mesh's axis inventory and order")


def batch_spec():
    """PartitionSpec for a (batch, ...) input: batch shards over dp and fsdp
    (fsdp acts as extra data parallelism for the forward pass)."""
    from jax.sharding import PartitionSpec as P
    return P(("dp", "fsdp"))


def fsdp_sharded_leaves(params):
    """Leaves of ``params`` that are genuinely ZeRO-sharded over the 'fsdp'
    mesh axis: their addressable shard is strictly smaller than the global
    leaf AND their PartitionSpec names 'fsdp'. Used by tests and the driver
    dryrun to PROVE fsdp>1 shards parameters rather than trusting the spec.
    """
    import jax
    return [
        p for p in jax.tree_util.tree_leaves(params)
        if p.addressable_shards[0].data.size < p.size
        and "fsdp" in str(p.sharding.spec)
    ]


def param_shardings(mesh, abstract_variables, rules=TRANSFORMER_RULES):
    """NamedShardings for a flax variables pytree annotated with
    with_logical_partitioning."""
    import jax
    from flax import linen as nn
    from jax.sharding import NamedSharding, PartitionSpec as P

    logical = nn.get_partition_spec(abstract_variables)
    mesh_specs = nn.logical_to_mesh(logical, rules)
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), mesh_specs,
        is_leaf=lambda x: isinstance(x, P))
