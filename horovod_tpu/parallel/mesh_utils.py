"""Training-mesh construction.

The reference's GLOBAL/LOCAL/CROSS communicator triple
(/root/reference/horovod/common/common.h:111) generalizes on TPU to an
N-dimensional device mesh whose axis order encodes interconnect locality:
the **last** axes map to adjacent devices (ICI neighbors), the **first** axis
crosses slices (DCN). Collectives over trailing axes ride ICI; leading axes
ride DCN — so put tp/sp (latency-critical, per-layer) innermost and dp
(once-per-step gradient reduction) outermost, the standard scaling recipe.
"""

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np


class MeshShapeError(ValueError):
    """A mesh (re)shape request that cannot produce a valid device grid —
    survivor count not divisible by the protected inner axes, an unknown
    axis name in a spec, or a policy that refuses the change. Raised
    *before* any pjit trace, so the operator sees the policy and the
    counts instead of a shape error deep inside XLA."""


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Sizes of each parallelism axis; -1 on dp means "absorb the rest"."""
    dp: int = -1      # data parallel (gradient allreduce, DCN-tolerant)
    fsdp: int = 1     # sharded params/optimizer (ZeRO-3 style)
    pp: int = 1       # pipeline stages
    ep: int = 1       # expert parallel
    sp: int = 1       # sequence/context parallel (ring attention)
    tp: int = 1       # tensor parallel (innermost, ICI-adjacent)


AXIS_ORDER = ("dp", "fsdp", "pp", "ep", "sp", "tp")

#: reshape policies for :func:`plan_reshape` (HVD_TPU_MESH_RESHAPE_POLICY)
RESHAPE_POLICIES = ("shrink", "degrade", "strict")


@dataclasses.dataclass(frozen=True)
class ReshapePlan:
    """Outcome of :func:`plan_reshape`: the new mesh config, the policy
    that produced it, the direction relative to the old shape ('down',
    'up', or 'none'), how many survivors the new mesh ``used``, and how
    many it ``dropped`` (non-zero only under the ``degrade`` policy)."""
    config: MeshConfig
    policy: str
    direction: str
    used: int
    dropped: int


def mesh_total(config: MeshConfig) -> int:
    """Devices a fully resolved config occupies (dp must not be -1)."""
    if config.dp <= 0:
        raise MeshShapeError(
            f"mesh config {config} has unresolved dp={config.dp}; resolve "
            "dp against a concrete device count first")
    return int(np.prod([getattr(config, a) for a in AXIS_ORDER]))


def mesh_config_from_spec(spec: str) -> MeshConfig:
    """Parse an ``axis=size`` comma list (``"dp=2,fsdp=2"``) into a
    MeshConfig. Unnamed axes default to 1 (an explicit spec is explicit —
    dp is not left at -1 unless the spec says ``dp=-1``)."""
    sizes = {a: 1 for a in AXIS_ORDER}
    if not spec or not spec.strip():
        raise MeshShapeError("empty mesh spec; expected 'axis=size' comma "
                             f"list over axes {AXIS_ORDER}")
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        axis, sep, value = part.partition("=")
        axis = axis.strip()
        if not sep or axis not in AXIS_ORDER:
            raise MeshShapeError(
                f"unknown mesh axis {axis!r} in spec {spec!r}; valid axes "
                f"(outermost first) are {AXIS_ORDER}")
        try:
            sizes[axis] = int(value)
        except ValueError:
            raise MeshShapeError(
                f"mesh axis {axis!r} has non-integer size {value!r} in "
                f"spec {spec!r}") from None
    return MeshConfig(**sizes)


def _inner_product(config: MeshConfig) -> int:
    """Product of the protected axes (everything but dp and fsdp): the
    reshape policies never break pp/ep/sp/tp groups — a tp-sharded matmul
    cannot lose a shard-holder and stay a matmul."""
    return int(np.prod([getattr(config, a) for a in AXIS_ORDER
                        if a not in ("dp", "fsdp")]))


def plan_reshape(config: MeshConfig, survivors: int,
                 policy: Optional[str] = None) -> ReshapePlan:
    """Compute the mesh shape ``survivors`` devices/hosts re-form into.

    Policies (``HVD_TPU_MESH_RESHAPE_POLICY``):

    * ``shrink`` (default): shrink dp first, then fsdp, never the inner
      (pp/ep/sp/tp) axes. Survivors must divide into whole inner groups
      or :class:`MeshShapeError` is raised.
    * ``degrade``: like shrink, but a survivor count that doesn't divide
      evenly drops a remainder (whole dp replica groups' worth of
      capacity idles) instead of aborting — ``plan.dropped`` says how
      many survivors sit out.
    * ``strict``: any change of shape raises :class:`MeshShapeError`
      (the operator wants a failed host to fail the job).

    ``config.dp == -1`` is resolved against ``survivors`` (first
    generation); the result's direction is ``'none'`` — adopting an
    initial shape is not a reshape.
    """
    if policy is None:
        from .. import config as _config
        policy = str(_config.live_config().get(
            _config.MESH_RESHAPE_POLICY)).strip().lower()
    if policy not in RESHAPE_POLICIES:
        raise MeshShapeError(
            f"unknown mesh reshape policy {policy!r}; valid policies are "
            f"{RESHAPE_POLICIES}")
    survivors = int(survivors)
    inner = _inner_product(config)
    if survivors < inner:
        raise MeshShapeError(
            f"policy {policy!r} cannot form a mesh from {survivors} "
            f"survivor(s): the protected inner axes (pp*ep*sp*tp) need "
            f"{inner} devices per replica group and are never broken")

    initial = config.dp <= 0
    old_total = None if initial else mesh_total(config)
    if not initial and survivors == old_total:
        return ReshapePlan(config=config, policy=policy, direction="none",
                           used=survivors, dropped=0)
    if not initial and policy == "strict":
        raise MeshShapeError(
            f"policy 'strict' refuses to reshape: mesh "
            f"{dataclasses.asdict(config)} needs {old_total} devices but "
            f"{survivors} survive")

    fsdp = max(int(config.fsdp), 1)
    if policy == "degrade":
        new_fsdp = fsdp
        while survivors // (new_fsdp * inner) < 1:
            new_fsdp -= 1   # terminates: survivors >= inner, so fsdp=1 fits
        new_dp = survivors // (new_fsdp * inner)
        used = new_dp * new_fsdp * inner
    else:
        if survivors % inner != 0:
            raise MeshShapeError(
                f"policy {policy!r} cannot reshape to {survivors} "
                f"survivor(s): not divisible by the protected inner-axes "
                f"product {inner} (pp*ep*sp*tp); use policy 'degrade' to "
                f"drop the remainder instead of aborting")
        q = survivors // inner
        if policy == "strict" and q % fsdp != 0:
            raise MeshShapeError(
                f"policy 'strict' cannot resolve dp: {survivors} "
                f"survivor(s) leave {q} inner groups, not divisible by "
                f"fsdp={fsdp}")
        new_fsdp = fsdp if q % fsdp == 0 else max(
            f for f in range(1, fsdp + 1) if q % f == 0)
        new_dp = q // new_fsdp
        used = survivors
    new_config = dataclasses.replace(config, dp=new_dp, fsdp=new_fsdp)
    if initial:
        direction = "none"
    else:
        direction = "down" if used < old_total else "up"
    return ReshapePlan(config=new_config, policy=policy, direction=direction,
                       used=used, dropped=survivors - used)


def replica_groups(world_size: int, dp: int) -> List[List[int]]:
    """Rank groups holding bit-identical parameter replicas.

    With dp outermost (AXIS_ORDER), rank = dp_index * (world/dp) +
    inner_index — so ranks sharing an inner index across dp slices hold
    the same tp/fsdp shard and may be fingerprint-compared; ranks in
    different groups hold *different* shards and must not be.
    """
    if dp <= 0 or world_size <= 0 or world_size % dp != 0:
        raise MeshShapeError(
            f"cannot form replica groups: world size {world_size} not "
            f"divisible into dp={dp} replicas")
    stride = world_size // dp
    return [[g + k * stride for k in range(dp)] for g in range(stride)]


def replica_group_of(rank: int, world_size: int, dp: int) -> int:
    """Index (into :func:`replica_groups`) of the group ``rank`` is in."""
    if dp <= 0 or world_size <= 0 or world_size % dp != 0:
        raise MeshShapeError(
            f"cannot form replica groups: world size {world_size} not "
            f"divisible into dp={dp} replicas")
    return int(rank) % (world_size // dp)


def make_training_mesh(config: MeshConfig = MeshConfig(),
                       devices=None):
    """Build a Mesh with axes ('dp','fsdp','pp','ep','sp','tp').

    Axes of size 1 are kept (harmless to XLA, simplifies downstream specs).
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    sizes = {a: getattr(config, a) for a in AXIS_ORDER}
    fixed = int(np.prod([s for a, s in sizes.items() if a != "dp" and s > 0]))
    if sizes["dp"] == -1:
        if n % fixed != 0:
            raise ValueError(
                f"{n} devices not divisible by non-dp axes product {fixed}")
        sizes["dp"] = n // fixed
    total = int(np.prod(list(sizes.values())))
    if total != n:
        raise ValueError(
            f"mesh sizes {sizes} use {total} devices but {n} are available")
    arr = np.array(devices).reshape([sizes[a] for a in AXIS_ORDER])
    return Mesh(arr, AXIS_ORDER)


# Logical-axis -> mesh-axis rules for the transformer in models/transformer.py
# (flax nn.with_logical_partitioning names). 'embed' stays replicated across
# tp (activations shard over it only in sequence-parallel regions); params
# additionally shard over fsdp on their largest axis.
TRANSFORMER_RULES: Tuple[Tuple[str, Optional[str]], ...] = (
    ("vocab", "tp"),
    ("heads", "tp"),
    ("mlp", "tp"),
    ("embed", "fsdp"),
    ("kv", None),
)


def require_axes(mesh, *axis_names: str):
    """Fail fast when an axis name is not on ``mesh``.

    The runtime counterpart of the ``mesh-axis`` lint
    (docs/static_analysis.md): the lint proves *literal* axis names
    resolve, this check covers names that arrive in variables. Without
    it a typo'd axis surfaces as an opaque trace-time NameError deep
    inside shard_map — or, worse, a mispaired collective.
    """
    declared = tuple(mesh.axis_names)
    missing = [a for a in axis_names if a and a not in declared]
    if missing:
        raise ValueError(
            f"axis name(s) {missing} not on this mesh (declared axes, "
            f"outermost first: {declared}); pipeline/MoE stages must "
            f"agree on the mesh's axis inventory and order")


def batch_spec():
    """PartitionSpec for a (batch, ...) input: batch shards over dp and fsdp
    (fsdp acts as extra data parallelism for the forward pass)."""
    from jax.sharding import PartitionSpec as P
    return P(("dp", "fsdp"))


def fsdp_sharded_leaves(params):
    """Leaves of ``params`` that are genuinely ZeRO-sharded over the 'fsdp'
    mesh axis: their addressable shard is strictly smaller than the global
    leaf AND their PartitionSpec names 'fsdp'. Used by tests and the driver
    dryrun to PROVE fsdp>1 shards parameters rather than trusting the spec.
    """
    import jax
    return [
        p for p in jax.tree_util.tree_leaves(params)
        if p.addressable_shards[0].data.size < p.size
        and "fsdp" in str(p.sharding.spec)
    ]


def param_shardings(mesh, abstract_variables, rules=TRANSFORMER_RULES):
    """NamedShardings for a flax variables pytree annotated with
    with_logical_partitioning."""
    import jax
    from flax import linen as nn
    from jax.sharding import NamedSharding, PartitionSpec as P

    logical = nn.get_partition_spec(abstract_variables)
    mesh_specs = nn.logical_to_mesh(logical, rules)
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), mesh_specs,
        is_leaf=lambda x: isinstance(x, P))
