"""Composed distributed training step for the transformer.

This is the TPU-native "DistributedOptimizer end-to-end": one jitted SPMD
program over a (dp, fsdp, pp, ep, sp, tp) mesh where

* parameters shard by their logical axes (tp/fsdp) — pjit auto mode;
* the batch shards over (dp, fsdp), the sequence over sp;
* attention runs ring (or Ulysses) context-parallel via a *nested* manual
  shard_map over just the 'sp' axis (axis_names={'sp'}), while dp/fsdp/tp
  stay in XLA's automatic sharding propagation — so the gradient allreduce,
  tensor-parallel collectives, and the ring ppermutes all come out of one
  compilation;
* gradients need no explicit reduction (auto mode supplies them globally
  correct; DistributedOptimizer mode 2).
"""

import dataclasses
from typing import Optional

import numpy as np


def sharded_attention(mesh, kind: str = "ring", causal: bool = True):
    """Build a TransformerConfig.attention_fn running context-parallel over
    the mesh's 'sp' axis, nested inside auto dp/fsdp/tp sharding."""
    import jax
    from jax.sharding import PartitionSpec as P

    from .ring_attention import ring_attention
    from .ulysses import ulysses_attention

    if mesh.shape.get("sp", 1) == 1:
        return None  # fall back to the model's default full attention

    def fn(q, k, v, mask, dtype):
        del mask  # global causal masking computed from ring positions

        def inner(ql, kl, vl):
            if kind == "ring":
                return ring_attention(ql, kl, vl, "sp", causal=causal,
                                      out_dtype=dtype)
            return ulysses_attention(ql, kl, vl, "sp", causal=causal,
                                     out_dtype=dtype)

        return jax.shard_map(
            inner, mesh=mesh, in_specs=P(None, "sp"),
            out_specs=P(None, "sp"), axis_names={"sp"})(q, k, v)
    return fn


@dataclasses.dataclass
class TrainStepBundle:
    step: object            # jitted (params, opt_state, tokens, targets) ->
    #                         (params, opt_state, loss)
    params: object
    opt_state: object
    batch_sharding: object
    mesh: object


def make_transformer_train_step(cfg, mesh, optimizer=None,
                                attention_kind: str = "ring",
                                rules=None) -> TrainStepBundle:
    """Build model + sharded params + jitted train step over ``mesh``.

    ``cfg``: models.transformer.TransformerConfig (attention_fn is replaced
    with the sp-parallel one when the mesh has sp > 1).
    """
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.models import Transformer
    from .mesh_utils import TRANSFORMER_RULES, param_shardings

    rules = rules or TRANSFORMER_RULES
    attn = sharded_attention(mesh, kind=attention_kind)
    cfg = dataclasses.replace(cfg, attention_fn=attn)
    model = Transformer(cfg)

    optimizer = optimizer or optax.adamw(1e-3)
    opt = hvd.DistributedOptimizer(optimizer)

    sp = mesh.shape.get("sp", 1)
    S = cfg.max_seq_len
    if S % max(sp, 1) != 0:
        raise ValueError(f"seq len {S} not divisible by sp={sp}")
    tok0 = jnp.zeros((1, S), jnp.int32)

    abstract = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), tok0))
    shardings = param_shardings(mesh, abstract, rules)
    variables = jax.jit(
        lambda: model.init(jax.random.PRNGKey(0), tok0),
        out_shardings=shardings)()
    params = variables["params"]
    opt_state = opt.init(params)

    batch_sharding = NamedSharding(mesh, P(("dp", "fsdp"), "sp"))

    def loss_fn(p, toks, tgts):
        logits = model.apply({"params": p}, toks)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tgts).mean()

    def _step(p, s, toks, tgts):
        loss, grads = jax.value_and_grad(loss_fn)(p, toks, tgts)
        updates, s = opt.update(grads, s, p)
        return optax.apply_updates(p, updates), s, loss

    step = jax.jit(_step, donate_argnums=(0, 1))
    return TrainStepBundle(step=step, params=params, opt_state=opt_state,
                           batch_sharding=batch_sharding, mesh=mesh)
