"""Composed distributed training step for the transformer.

This is the TPU-native "DistributedOptimizer end-to-end": one jitted SPMD
program over a (dp, fsdp, pp, ep, sp, tp) mesh where

* parameters shard by their logical axes (tp/fsdp) — pjit auto mode;
* the batch shards over (dp, fsdp), the sequence over sp;
* attention runs ring (or Ulysses) context-parallel via a *nested* manual
  shard_map over just the 'sp' axis (axis_names={'sp'}), while dp/fsdp/tp
  stay in XLA's automatic sharding propagation — so the gradient allreduce,
  tensor-parallel collectives, and the ring ppermutes all come out of one
  compilation;
* gradients need no explicit reduction (auto mode supplies them globally
  correct; DistributedOptimizer mode 2).
"""

import dataclasses
from typing import Any, Dict, Optional

import numpy as np

from .. import faults as _faults

# Chaos site for the sharded train step: one hit per run_mesh_step()
# call, fired BEFORE the jitted step executes — a ``crash`` rule here
# (``worker.mesh:crash:step=N:rank=R``) hard-kills a rank mid-sharded-
# step, the deterministic stand-in for losing a host out of a
# dp x fsdp x tp mesh. The work of the killed step is lost on every
# rank exactly as a real host loss would lose it; survivors re-form the
# reshaped mesh and restore the last sharded checkpoint through the
# resharding reader (docs/elastic.md, mesh-aware recovery).
_FP_MESH = _faults.FaultPoint("worker.mesh")


def sharded_attention(mesh, kind: str = "ring", causal: bool = True):
    """Build a TransformerConfig.attention_fn running context-parallel over
    the mesh's 'sp' axis, nested inside auto dp/fsdp/tp sharding."""
    import jax
    from jax.sharding import PartitionSpec as P

    from .ring_attention import ring_attention
    from .ulysses import ulysses_attention

    if mesh.shape.get("sp", 1) == 1:
        return None  # fall back to the model's default full attention

    def fn(q, k, v, mask, dtype):
        del mask  # global causal masking computed from ring positions

        def inner(ql, kl, vl):
            if kind == "ring":
                return ring_attention(ql, kl, vl, "sp", causal=causal,
                                      out_dtype=dtype)
            return ulysses_attention(ql, kl, vl, "sp", causal=causal,
                                     out_dtype=dtype)

        return jax.shard_map(
            inner, mesh=mesh, in_specs=P(None, "sp"),
            out_specs=P(None, "sp"), axis_names={"sp"})(q, k, v)
    return fn


@dataclasses.dataclass
class TrainStepBundle:
    step: object            # jitted (params, opt_state, tokens, targets) ->
    #                         (params, opt_state, loss)
    params: object
    opt_state: object
    batch_sharding: object
    mesh: object


def make_transformer_train_step(cfg, mesh, optimizer=None,
                                attention_kind: str = "ring",
                                rules=None) -> TrainStepBundle:
    """Build model + sharded params + jitted train step over ``mesh``.

    ``cfg``: models.transformer.TransformerConfig (attention_fn is replaced
    with the sp-parallel one when the mesh has sp > 1).
    """
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.models import Transformer
    from .mesh_utils import TRANSFORMER_RULES, param_shardings

    rules = rules or TRANSFORMER_RULES
    attn = sharded_attention(mesh, kind=attention_kind)
    cfg = dataclasses.replace(cfg, attention_fn=attn)
    model = Transformer(cfg)

    optimizer = optimizer or optax.adamw(1e-3)
    opt = hvd.DistributedOptimizer(optimizer)

    sp = mesh.shape.get("sp", 1)
    S = cfg.max_seq_len
    if S % max(sp, 1) != 0:
        raise ValueError(f"seq len {S} not divisible by sp={sp}")
    tok0 = jnp.zeros((1, S), jnp.int32)

    abstract = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), tok0))
    shardings = param_shardings(mesh, abstract, rules)
    variables = jax.jit(
        lambda: model.init(jax.random.PRNGKey(0), tok0),
        out_shardings=shardings)()
    params = variables["params"]
    opt_state = opt.init(params)

    batch_sharding = NamedSharding(mesh, P(("dp", "fsdp"), "sp"))

    def loss_fn(p, toks, tgts):
        logits = model.apply({"params": p}, toks)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tgts).mean()

    def _step(p, s, toks, tgts):
        loss, grads = jax.value_and_grad(loss_fn)(p, toks, tgts)
        updates, s = opt.update(grads, s, p)
        return optax.apply_updates(p, updates), s, loss

    step = jax.jit(_step, donate_argnums=(0, 1))
    return TrainStepBundle(step=step, params=params, opt_state=opt_state,
                           batch_sharding=batch_sharding, mesh=mesh)


# -- mesh-aware recovery: run / save / restore / drain the sharded train
#    state (docs/elastic.md). These are the pieces the elastic drill
#    composes: the fault site above kills a rank mid-step, the driver
#    replans the mesh, and the survivor generation restores step-exact
#    through the resharding checkpoint reader.


def train_state_tree(bundle: TrainStepBundle) -> Dict[str, Any]:
    """The checkpointable pytree of a :class:`TrainStepBundle` — exactly
    the state a surviving mesh must restore to resume step-exact."""
    return {"params": bundle.params, "opt_state": bundle.opt_state}


def run_mesh_step(bundle: TrainStepBundle, tokens, targets):
    """One optimizer step through the bundle (fires the ``worker.mesh``
    chaos site first); updates the bundle in place, returns the loss."""
    _FP_MESH.fire()
    params, opt_state, loss = bundle.step(bundle.params, bundle.opt_state,
                                          tokens, targets)
    bundle.params = params
    bundle.opt_state = opt_state
    return loss


def save_mesh_train_state(manager, step: int, bundle: TrainStepBundle,
                          async_: bool = False) -> str:
    """Checkpoint the bundle's train state at ``step``. Sharded leaves
    are written shard-by-shard with their global offsets recorded, so a
    later restore can reassemble them onto a *different* mesh."""
    return manager.save(step, train_state_tree(bundle), async_=async_,
                        force=True)


def restore_mesh_train_state(manager, bundle: TrainStepBundle,
                             step: Optional[int] = None) -> Optional[int]:
    """Restore the newest (or ``step``'s) checkpoint into the bundle,
    re-staged onto the bundle's *current* shardings — the save-mesh and
    the restore-mesh are independent (checkpointing/snapshot.py records
    global offsets per shard). Returns the restored step, or None when
    the directory holds no checkpoint (fresh start)."""
    import jax

    target_step = manager.latest_step() if step is None else step
    if target_step is None:
        return None
    target = train_state_tree(bundle)
    shardings = jax.tree_util.tree_map(
        lambda leaf: getattr(leaf, "sharding", None), target)
    tree = manager.restore(step=target_step, target=target,
                           sharding=shardings, fallback=True)
    bundle.params = tree["params"]
    bundle.opt_state = tree["opt_state"]
    return target_step


def drain_mesh_train_state(manager, step: int,
                           bundle: TrainStepBundle) -> Optional[int]:
    """Preemption-drain the bundle: flush in-flight saves and force a
    final sync save of this host's shards if the newest committed step
    is older — the shard handoff of a graceful departure. The restore
    plan of the surviving mesh covers the departed host's fsdp shards
    from this checkpoint, never from peers that never held them."""
    return manager.drain_for_preemption(step, train_state_tree(bundle))
