"""Expert parallelism (Mixture-of-Experts) over the 'ep' mesh axis.

Absent from the reference (SURVEY.md §2.3). Switch-Transformer-style top-1
routing with capacity, dispatched between devices by a single pair of
all_to_alls — the canonical TPU MoE layout: experts shard over 'ep', each
device computes only its experts, and token movement is one all_to_all each
way (ICI-friendly; the dispatch/combine einsums land on the MXU).

Static shapes throughout (capacity fixed at trace time); overflowing tokens
are dropped and their outputs fall back to zero (residual connections carry
them), the standard capacity-factor semantics.
"""

from typing import Tuple

import jax
import jax.numpy as jnp


def route_top1(gate_logits, capacity: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-1 router (per device group).

    Args:
      gate_logits: (T, E) router scores for T tokens over E experts.
      capacity: max tokens per expert held by this group.
    Returns:
      dispatch: (T, E, C) one-hot dispatch mask.
      combine:  (T, E, C) combine weights (gate prob on the dispatch slot).
    """
    T, E = gate_logits.shape
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)                    # (T,)
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)  # (T, E)
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0        # (T, E)
    keep = (pos >= 0) & (pos < capacity)
    pos = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
    dispatch = (jax.nn.one_hot(pos, capacity, dtype=jnp.float32)
                * (onehot * keep)[..., None])              # (T, E, C)
    gate = jnp.sum(probs * onehot, axis=-1)                # (T,)
    combine = dispatch * gate[:, None, None]
    return dispatch, combine


def moe_mlp(x, gate_w, w_in, w_out, axis_name: str,
            capacity_factor: float = 1.25, act=jax.nn.gelu):
    """MoE FFN to use INSIDE shard_map over ``axis_name``.

    Args:
      x: (T_local, D) this device's tokens (flatten batch x seq first).
      gate_w: (D, E_total) router weights (replicated).
      w_in: (E_local, D, Hd) this device's expert up-projections.
      w_out: (E_local, Hd, D) this device's expert down-projections.
    Returns (T_local, D).
    """
    n = jax.lax.axis_size(axis_name)
    T, D = x.shape
    E_local = w_in.shape[0]
    E = E_local * n
    capacity = max(1, int(capacity_factor * T / E))

    logits = x @ gate_w.astype(x.dtype)                     # (T, E)
    dispatch, combine = route_top1(logits, capacity)

    xf = x.astype(jnp.float32)
    # local expert buffers: (E, C, D)
    buf = jnp.einsum("td,tec->ecd", xf, dispatch)
    # exchange: each device keeps rows for ITS experts from every peer:
    # (E, C, D) -> (E_local, n*C, D)
    buf = jax.lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=1,
                             tiled=True)
    h = jnp.einsum("ecd,edh->ech", buf.astype(x.dtype),
                   w_in.astype(x.dtype))
    h = act(h)
    out = jnp.einsum("ech,ehd->ecd", h, w_out.astype(x.dtype))
    # route back: (E_local, n*C, D) -> (E, C, D)
    out = jax.lax.all_to_all(out.astype(jnp.float32), axis_name,
                             split_axis=1, concat_axis=0, tiled=True)
    y = jnp.einsum("ecd,tec->td", out, combine)
    return y.astype(x.dtype)


class MoEMlp:
    """Parameter container + init for :func:`moe_mlp` (kept framework-thin;
    flax integration wraps this in a Module when needed)."""

    def __init__(self, d_model: int, hidden: int, num_experts: int):
        self.d_model = d_model
        self.hidden = hidden
        self.num_experts = num_experts

    def init(self, rng):
        k1, k2, k3 = jax.random.split(rng, 3)
        s = 0.02
        return {
            "gate_w": jax.random.normal(
                k1, (self.d_model, self.num_experts), jnp.float32) * s,
            "w_in": jax.random.normal(
                k2, (self.num_experts, self.d_model, self.hidden),
                jnp.float32) * s,
            "w_out": jax.random.normal(
                k3, (self.num_experts, self.hidden, self.d_model),
                jnp.float32) * s,
        }
