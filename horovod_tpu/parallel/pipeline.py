"""Pipeline parallelism over the 'pp' mesh axis.

Absent from the reference (SURVEY.md §2.3). GPipe-style microbatch schedule
expressed as a ``lax.scan`` over time steps with ``ppermute`` moving
activations to the next stage each step — the canonical TPU pipelining
pattern (activations hop one ICI neighbor per step; XLA overlaps the
permute with stage compute). Backward works by reverse-mode AD through the
scan: the reversed ppermute carries gradients stage-to-stage in the drain
order, so no hand-written backward schedule is needed.

Bubble fraction is (P-1)/(M+P-1) for P stages and M microbatches — pick
M >= 4*P for >80% utilization.
"""

from typing import Callable

import jax
import jax.numpy as jnp


def pipeline_shard_fn(stage_fn: Callable, stage_params, microbatches,
                      axis_name: str = "pp"):
    """Body to use INSIDE shard_map over ``axis_name``.

    Args:
      stage_fn: (params, x) -> y, the per-stage computation. All stages share
        this structure (e.g. a stack of identical decoder layers).
      stage_params: this device's stage parameters (already sharded by the
        surrounding shard_map in_specs).
      microbatches: (M, mb, ...) full input, replicated across stages (only
        stage 0 consumes it).
    Returns (M, mb, ...) final-stage outputs, replicated across stages.
    """
    P = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    T = M + P - 1
    mb_shape = microbatches.shape[1:]
    perm_fwd = [(p, p + 1) for p in range(P - 1)]

    def step(carry, t):
        incoming = carry  # activation arriving at my stage this tick
        # stage 0 injects microbatch t (clamped; masked off after t >= M)
        inject = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, M - 1), axis=0, keepdims=False)
        x = jnp.where(idx == 0, inject, incoming)
        y = stage_fn(stage_params, x)
        # last stage's output for microbatch (t - P + 1); other stages pass on
        out_slot = jnp.where(idx == P - 1, y, jnp.zeros_like(y))
        nxt = jax.lax.ppermute(y, axis_name, perm_fwd)
        return nxt, out_slot

    init = jnp.zeros(mb_shape, microbatches.dtype)
    _, outs = jax.lax.scan(step, init, jnp.arange(T))  # (T, mb, ...)
    # replicate the last stage's results to every stage so downstream code
    # (loss on stage 0, metrics) sees them; zeros elsewhere make psum exact
    outs = jax.lax.psum(outs, axis_name)
    return jax.lax.slice_in_dim(outs, P - 1, T, axis=0)  # (M, mb, ...)


def pipeline_apply(stage_fn: Callable, stacked_params, microbatches, mesh,
                   axis_name: str = "pp"):
    """Convenience wrapper: shard_map over ``axis_name`` with stage params
    stacked on a leading axis of size P (params[p] = stage p).

    ``microbatches``: (M, mb, ...) global input. Returns (M, mb, ...).
    """
    from jax.sharding import PartitionSpec as Spec

    from .mesh_utils import require_axes
    require_axes(mesh, axis_name)

    def body(params, mb):
        # shard_map leaves a leading axis of size 1 on the stacked params
        params = jax.tree_util.tree_map(lambda a: a[0], params)
        return pipeline_shard_fn(stage_fn, params, mb, axis_name)

    in_specs = (jax.tree_util.tree_map(lambda _: Spec(axis_name),
                                       stacked_params),
                Spec())
    return jax.shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=Spec(),
        check_vma=False)(stacked_params, microbatches)
