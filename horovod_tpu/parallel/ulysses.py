"""Ulysses-style sequence parallelism: all_to_all over the head axis.

Absent from the reference (SURVEY.md §2.3). The other long-context strategy:
instead of rotating K/V blocks (ring attention), one all_to_all re-shards
the activations from sequence-sharded to head-sharded, each device computes
*full-sequence* attention for its subset of heads, and a second all_to_all
restores sequence sharding. Two collectives total (vs n-1 ppermutes), at the
cost of requiring num_heads % sp_size == 0 and full-sequence scores memory
per head — the right trade on ICI-rich TPU slices for moderate sequence
lengths; ring attention wins for extreme lengths.
"""

import jax
import jax.numpy as jnp


def _seq_to_heads(x, axis_name):
    # (B, S_local, H, D) -> (B, S_full, H_local, D)
    return jax.lax.all_to_all(
        x, axis_name, split_axis=2, concat_axis=1, tiled=True)


def _heads_to_seq(x, axis_name):
    # (B, S_full, H_local, D) -> (B, S_local, H, D)
    return jax.lax.all_to_all(
        x, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = True,
                      attention_fn=None, out_dtype=None):
    """Exact attention with sequence sharded over ``axis_name``.

    Args:
      q, k, v: (B, S_local, H, D); H must be divisible by the axis size.
      attention_fn: inner full-sequence attention (defaults to the model's
        XLA softmax attention); receives (q, k, v, mask, dtype) with shapes
        (B, S_full, H_local, D). A Pallas flash-attention kernel slots in
        here unchanged.
    Returns (B, S_local, H, D).
    """
    out_dtype = out_dtype or q.dtype
    n = jax.lax.axis_size(axis_name)
    H = q.shape[2]
    if H % n != 0:
        raise ValueError(f"num_heads {H} not divisible by '{axis_name}' "
                         f"axis size {n}; use ring_attention instead")
    if attention_fn is None:
        from horovod_tpu.ops.flash_attention import use_pallas_default
        if use_pallas_default():
            # after the all_to_all each device holds the full sequence for
            # its head subset — exactly the flash kernel's shape
            from horovod_tpu.ops.flash_attention import flash_attention

            def attention_fn(qh, kh, vh, mask, dtype):
                del mask  # causal handled inside the kernel
                return flash_attention(qh, kh, vh, causal=causal,
                                       out_dtype=dtype, vma=(axis_name,))
        else:
            from horovod_tpu.models.transformer import _default_attention
            attention_fn = _default_attention
    qh = _seq_to_heads(q, axis_name)
    kh = _seq_to_heads(k, axis_name)
    vh = _seq_to_heads(v, axis_name)
    S = qh.shape[1]
    mask = jnp.tril(jnp.ones((S, S), jnp.bool_))[None, None] if causal else None
    oh = attention_fn(qh, kh, vh, mask, jnp.float32)
    return _heads_to_seq(oh.astype(out_dtype), axis_name)


def make_ulysses_attention(axis_name: str, causal: bool = True,
                           attention_fn=None):
    """Adapter for models.transformer.TransformerConfig.attention_fn."""
    def fn(q, k, v, mask, dtype):
        del mask
        return ulysses_attention(q, k, v, axis_name, causal=causal,
                                 attention_fn=attention_fn, out_dtype=dtype)
    return fn
