"""Hierarchical allreduce expressed in shard_map.

Reference: NCCLHierarchicalAllreduce
(/root/reference/horovod/common/ops/nccl_operations.cc:178-372) — NCCL
ReduceScatter within the node, MPI allreduce across nodes on the scattered
shards, NCCL Allgather back. On TPU the same bandwidth-optimal decomposition
is three XLA collectives over two mesh axes: the inner (ICI) axis carries
the scatter/gather, the outer (DCN) axis carries the cross-slice reduction
on 1/inner_size of the data.

XLA often produces this decomposition itself for a plain two-axis psum; the
explicit form exists for when the schedule matters (overlap tuning) and as
the building block for the autotuner's hierarchy on/off knob (reference
parameter_manager.h:38 HierarchicalAllreduce toggle).
"""


def hierarchical_allreduce(x, inner_axis: str, outer_axis: str,
                           scatter_dimension: int = 0):
    """Sum ``x`` over both axes: reduce_scatter(inner) -> psum(outer) ->
    all_gather(inner). Equivalent to psum over (inner, outer) but moves only
    1/inner_size of the bytes over the outer (slow) links.

    ``x``'s ``scatter_dimension`` must be divisible by the inner axis size.
    Use inside shard_map over a mesh containing both axes.
    """
    import jax

    scattered = jax.lax.psum_scatter(
        x, inner_axis, scatter_dimension=scatter_dimension, tiled=True)
    reduced = jax.lax.psum(scattered, outer_axis)
    return jax.lax.all_gather(
        reduced, inner_axis, axis=scatter_dimension, tiled=True)


def hierarchical_pmean(x, inner_axis: str, outer_axis: str,
                       scatter_dimension: int = 0):
    import jax
    n = jax.lax.axis_size(inner_axis) * jax.lax.axis_size(outer_axis)
    return hierarchical_allreduce(
        x, inner_axis, outer_axis, scatter_dimension) / n
