"""Ring attention: context parallelism over the 'sp' mesh axis.

Absent from the reference (SURVEY.md §2.3: no sequence/context parallelism
anywhere in the tree) — a required first-class capability of the TPU build.

Design (blockwise/flash attention over a device ring): the sequence is
sharded over the 'sp' axis; each device keeps its Q block resident and the
K/V blocks rotate around the ring via ``ppermute`` (one ICI hop per step, n
steps total). Attention is accumulated with the online-softmax recurrence in
fp32, so the result is exact — identical math to flash attention, with the
"blocks" living on different chips. Communication per step overlaps with the
block matmuls (XLA schedules ppermute async start/done around compute).

Causal masking is done at block granularity with global positions:
block from source device s attends fully when s < my_index, causally when
s == my_index, and is skipped (masked) when s > my_index.

Use inside shard_map with q/k/v sharded over 'sp' on the sequence axis:
shapes (B, S_local, H, D).
"""

import functools

import jax
import jax.numpy as jnp


NEG_INF = -1e30


def ring_attention(q, k, v, axis_name: str, causal: bool = True,
                   out_dtype=None):
    """Exact attention over sequence blocks distributed on ``axis_name``.

    Args:
      q, k, v: (B, S_local, H, D) per-device blocks (sequence axis sharded).
      axis_name: mesh axis carrying the sequence shards (the ring).
      causal: apply a causal mask using global positions.
    Returns (B, S_local, H, D) attention output for the local Q block.
    """
    out_dtype = out_dtype or q.dtype
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    B, S, H, D = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))

    qf = q.astype(jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def blockwise(carry, i):
        o, m, l, k_blk, v_blk = carry
        # source device whose block we hold at step i
        src = (my - i) % n
        # scores: (B, H, Sq, Sk) in fp32
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32))
        s = s * scale
        if causal:
            qpos = my * S + jnp.arange(S)             # (Sq,) global
            kpos = src * S + jnp.arange(S)            # (Sk,) global
            mask = qpos[:, None] >= kpos[None, :]     # (Sq, Sk)
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_blk = jnp.max(s, axis=-1)                   # (B, H, Sq)
        m_new = jnp.maximum(m, m_blk)
        # clamp so fully-masked rows (all NEG_INF) don't produce inf-inf
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)                     # (B, H, Sq)
        l_new = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32))
        o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
        # rotate K/V to the next device (skip the final, unused rotation
        # is harmless and keeps the scan body uniform)
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return (o_new, m_new, l_new, k_next, v_next), None

    # initial accumulators must be marked device-varying over the ring axis
    # for the scan carry to type-check under shard_map's VMA tracking
    def vary(x):
        return jax.lax.pcast(x, (axis_name,), to="varying")
    o0 = vary(jnp.zeros((B, S, H, D), jnp.float32))
    m0 = vary(jnp.full((B, H, S), NEG_INF, jnp.float32))
    l0 = vary(jnp.zeros((B, H, S), jnp.float32))
    (o, m, l, _, _), _ = jax.lax.scan(
        blockwise, (o0, m0, l0, k, v), jnp.arange(n))
    # fully-masked rows have l == 0 (can't happen with causal self-attn,
    # every query sees at least itself; guard anyway)
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(out_dtype)


def make_ring_attention(axis_name: str, causal: bool = True):
    """Adapter matching models.transformer.TransformerConfig.attention_fn's
    signature (q, k, v, mask, dtype). The local mask argument is ignored —
    global causal masking is computed from ring positions."""
    @functools.wraps(ring_attention)
    def fn(q, k, v, mask, dtype):
        del mask
        return ring_attention(q, k, v, axis_name, causal=causal,
                              out_dtype=dtype)
    return fn
