"""Ring attention: context parallelism over the 'sp' mesh axis.

Absent from the reference (SURVEY.md §2.3: no sequence/context parallelism
anywhere in the tree) — a required first-class capability of the TPU build.

Design (blockwise/flash attention over a device ring): the sequence is
sharded over the 'sp' axis; each device keeps its Q block resident and the
K/V blocks rotate around the ring via ``ppermute`` (one ICI hop per step, n
steps total). Attention is accumulated with the online-softmax recurrence in
fp32, so the result is exact — identical math to flash attention, with the
"blocks" living on different chips. Communication per step overlaps with the
block matmuls (XLA schedules ppermute async start/done around compute).

Causal masking is done at block granularity with global positions:
block from source device s attends fully when s < my_index, causally when
s == my_index, and is skipped (masked) when s > my_index.

Use inside shard_map with q/k/v sharded over 'sp' on the sequence axis:
shapes (B, S_local, H, D).
"""

import functools

import jax
import jax.numpy as jnp


NEG_INF = -1e30


def ring_attention(q, k, v, axis_name: str, causal: bool = True,
                   out_dtype=None, impl: str = "auto"):
    """Exact attention over sequence blocks distributed on ``axis_name``.

    Args:
      q, k, v: (B, S_local, H, D) per-device blocks (sequence axis sharded).
      axis_name: mesh axis carrying the sequence shards (the ring).
      causal: apply a causal mask using global positions.
      impl: "flash" = Pallas flash kernel per ring step (TPU hot path),
        "xla" = blockwise einsum recurrence, "auto" = flash on TPU.
    Returns (B, S_local, H, D) attention output for the local Q block.
    """
    if impl == "auto":
        from ..ops.flash_attention import use_pallas_default
        impl = "flash" if use_pallas_default() else "xla"
    if impl == "flash":
        return ring_attention_flash(q, k, v, axis_name, causal=causal,
                                    out_dtype=out_dtype)
    out_dtype = out_dtype or q.dtype
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    B, S, H, D = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))

    qf = q.astype(jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def blockwise(carry, i):
        o, m, l, k_blk, v_blk = carry
        # source device whose block we hold at step i
        src = (my - i) % n
        # scores: (B, H, Sq, Sk) in fp32
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32))
        s = s * scale
        if causal:
            qpos = my * S + jnp.arange(S)             # (Sq,) global
            kpos = src * S + jnp.arange(S)            # (Sk,) global
            mask = qpos[:, None] >= kpos[None, :]     # (Sq, Sk)
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_blk = jnp.max(s, axis=-1)                   # (B, H, Sq)
        m_new = jnp.maximum(m, m_blk)
        # clamp so fully-masked rows (all NEG_INF) don't produce inf-inf
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)                     # (B, H, Sq)
        l_new = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32))
        o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
        # rotate K/V to the next device (skip the final, unused rotation
        # is harmless and keeps the scan body uniform)
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return (o_new, m_new, l_new, k_next, v_next), None

    # initial accumulators must be marked device-varying over the ring axis
    # for the scan carry to type-check under shard_map's VMA tracking
    def vary(x):
        return jax.lax.pcast(x, (axis_name,), to="varying")
    o0 = vary(jnp.zeros((B, S, H, D), jnp.float32))
    m0 = vary(jnp.full((B, H, S), NEG_INF, jnp.float32))
    l0 = vary(jnp.zeros((B, H, S), jnp.float32))
    (o, m, l, _, _), _ = jax.lax.scan(
        blockwise, (o0, m0, l0, k, v), jnp.arange(n))
    # fully-masked rows have l == 0 (can't happen with causal self-attn,
    # every query sees at least itself; guard anyway)
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(out_dtype)


def ring_attention_flash(q, k, v, axis_name: str, causal: bool = True,
                         out_dtype=None, interpret=None,
                         block_q: int = 512, block_k: int = 128):
    """Ring attention with the Pallas flash kernel as the per-step block
    engine (ops/flash_attention.py).

    Each ring step computes this device's Q block against the currently-held
    K/V block with the flash kernel — which returns (out_i, lse_i), both
    differentiable — and merges the partials with the standard log-sum-exp
    combine::

        lse' = logaddexp(lse, lse_i)
        o'   = o * exp(lse - lse') + o_i * exp(lse_i - lse')

    Steps whose K block is entirely in the causal future yield lse_i ~ -1e30
    and contribute exp(-big) = 0, so the merge is uniform (no data-dependent
    control flow — one compiled SPMD program). ``jax.checkpoint`` wraps the
    step so the backward re-runs the kernel instead of storing every rotated
    K/V block — memory stays O(S_local) like the forward, the standard ring
    attention trade.
    """
    out_dtype = out_dtype or q.dtype
    from ..ops.flash_attention import (flash_attention_with_lse,
                                       use_pallas_default)
    if interpret is None:
        interpret = not use_pallas_default()
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    B, S, H, D = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(i, o, lse, k_blk, v_blk):
        src = (my - i) % n
        o_i, lse_i = flash_attention_with_lse(
            q, k_blk, v_blk, causal=causal,
            q_offset=my * S, k_offset=src * S,
            block_q=block_q, block_k=block_k, interpret=interpret,
            out_dtype=jnp.float32, vma=(axis_name,))
        lse_new = jnp.logaddexp(lse, lse_i)
        w_old = jnp.exp(lse - lse_new)[..., None]        # (B, S, H, 1)
        w_new = jnp.exp(lse_i - lse_new)[..., None]
        o = o * w_old + o_i * w_new
        if i + 1 < n:  # final rotation unnecessary
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return o, lse_new, k_blk, v_blk

    # remat each step on the compiled path: the backward re-runs the kernel
    # instead of storing every rotated K/V block, keeping memory O(S_local)
    if not interpret:
        step = jax.checkpoint(step, static_argnums=(0,))

    def vary(x):
        return jax.lax.pcast(x, (axis_name,), to="varying")
    o = vary(jnp.zeros((B, S, H, D), jnp.float32))
    lse = vary(jnp.full((B, S, H), NEG_INF, jnp.float32))
    k_blk, v_blk = k, v
    # unrolled ring (n is static = axis size): one pallas call per step,
    # ppermute overlapped with the next step's compute by XLA's scheduler
    for i in range(n):
        o, lse, k_blk, v_blk = step(i, o, lse, k_blk, v_blk)
    return o.astype(out_dtype)


def make_ring_attention(axis_name: str, causal: bool = True):
    """Adapter matching models.transformer.TransformerConfig.attention_fn's
    signature (q, k, v, mask, dtype). The local mask argument is ignored —
    global causal masking is computed from ring positions."""
    @functools.wraps(ring_attention)
    def fn(q, k, v, mask, dtype):
        del mask
        return ring_attention(q, k, v, axis_name, causal=causal,
                              out_dtype=dtype)
    return fn
