"""Exception types for horovod_tpu.

TPU-native equivalents of the reference's error surface
(/root/reference/horovod/common/exceptions.py:17-34 and the
DUPLICATE_NAME_ERROR / shape-mismatch errors raised by the C++ controller,
/root/reference/horovod/common/controller.cc:378-611).
"""


class HorovodInternalError(RuntimeError):
    """Internal error raised when a collective fails mid-flight.

    In elastic mode this triggers state restore + re-initialization
    (reference: horovod/common/exceptions.py:21, common/elastic.py:147-168).
    """


class HostsUpdatedInterrupt(Exception):
    """Raised in elastic mode when cluster membership changed.

    The current batch results are kept (no rollback) and the job
    re-initializes on the new set of hosts
    (reference: horovod/common/exceptions.py:26-34).
    """

    def __init__(self, skip_sync=False):
        super().__init__()
        self.skip_sync = skip_sync


class TensorValidationError(ValueError):
    """A submitted tensor failed validation against the named-tensor table.

    Covers the reference controller's error responses: duplicate in-flight
    name, mismatched dtype/shape/op across ranks
    (reference: horovod/common/controller.cc:378-611, tensor_queue.cc
    DUPLICATE_NAME_ERROR).
    """


class DuplicateNameError(TensorValidationError):
    """Same tensor name submitted while a prior submission is in flight."""


class NotInitializedError(RuntimeError):
    """An API that requires init() was called before init()."""

    def __init__(self, what="horovod_tpu"):
        super().__init__(
            f"{what} has not been initialized; call horovod_tpu.init() first.")


class StallError(HorovodInternalError):
    """Raised (optionally) by the stall inspector after the shutdown deadline.

    Subclasses :class:`HorovodInternalError` so the elastic retry loop
    treats a stalled collective (usually a dead or wedged peer) as a
    recoverable fault: restore committed state and re-initialize
    (reference: stall shutdown aborts the job, stall_inspector.cc:31-90;
    elastic recovery then restarts it — here the two compose directly).
    """
