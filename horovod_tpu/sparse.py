"""Sparse (embedding-style) gradient reduction.

Reference: TF IndexedSlices gradients are allreduced by allgathering values
and indices across ranks (/root/reference/horovod/tensorflow/
__init__.py:87-102 `_allreduce_cond` sparse branch), because summing ragged
index sets is cheaper as a gather; Torch exposes
``sparse_as_dense`` to densify instead (torch/optimizer.py DistributedOptimizer
argument). Both surfaces exist here:

* :func:`allreduce_sparse` — gather-based: returns the concatenated
  (indices, values) pairs from every process, values pre-divided for
  Average. Duplicate indices are legal (the consumer scatter-adds), exactly
  like TF IndexedSlices semantics.
* :func:`sparse_to_dense` / :func:`allreduce_sparse_as_dense` — densify and
  ride the dense allreduce (HOROVOD_SPARSE_AS_DENSE semantics).

On the compiled plane, embedding gradients under pjit are handled by XLA's
scatter fusion and need no special casing — these helpers serve the eager
host plane.
"""

from typing import NamedTuple, Optional

import jax.numpy as jnp
import numpy as np


class SparseGradient(NamedTuple):
    """IndexedSlices-shaped triple: ``values[i]`` is the gradient row for
    ``dense_shape``-indexed row ``indices[i]``."""
    indices: jnp.ndarray    # (nnz,) int
    values: jnp.ndarray     # (nnz, ...) rows
    dense_shape: tuple


def allreduce_sparse(sparse: SparseGradient, average: bool = True,
                     name: Optional[str] = None,
                     process_set=None) -> SparseGradient:
    """Allreduce of a sparse gradient by double allgather (reference:
    tensorflow/__init__.py:87-102). Per-process nnz may differ (ragged
    allgather). Returns the global (indices, values) with values scaled by
    1/size when ``average``."""
    from . import basics as _basics
    from . import collectives as _c
    w = _basics.world()
    name = name or "horovod_tpu.sparse"
    values = jnp.asarray(sparse.values)
    if average:
        wm = process_set or w.world_mesh
        values = values / wm.num_procs
    gathered_values = _c.allgather(values, name=name + ".values",
                                   process_set=process_set)
    gathered_indices = _c.allgather(jnp.asarray(sparse.indices),
                                    name=name + ".indices",
                                    process_set=process_set)
    return SparseGradient(gathered_indices, gathered_values,
                          sparse.dense_shape)


def sparse_to_dense(sparse: SparseGradient) -> jnp.ndarray:
    """Scatter-add the rows into a dense array (duplicate indices sum)."""
    dense = jnp.zeros(sparse.dense_shape, sparse.values.dtype)
    return dense.at[sparse.indices].add(sparse.values)


def allreduce_sparse_as_dense(sparse: SparseGradient, average: bool = True,
                              name: Optional[str] = None,
                              process_set=None) -> jnp.ndarray:
    """Densify then dense-allreduce (reference sparse_as_dense knob,
    torch/optimizer.py). Better when nnz approaches the dense size."""
    from . import collectives as _c
    dense = sparse_to_dense(sparse)
    op = _c.Average if average else _c.Sum
    return _c.allreduce(dense, op=op,
                        name=name or "horovod_tpu.sparse.dense",
                        process_set=process_set)
