"""On-disk layout, integrity manifest, and commit protocol.

One checkpoint step is a directory::

    <root>/step_0000000042/
        shards/
            00000.full.bin          # leaf 0, unsharded
            00001.0.bin             # leaf 1, shard starting at row 0
            00001.8.bin             # leaf 1, shard starting at row 8
            index.0.json            # per-process shard table (multi-host)
        manifest.json               # step, world, per-leaf layout+checksums
        COMMIT                      # written LAST, atomic rename

Crash consistency comes from ordering, not locking:

1. every shard file is written to a ``.tmp`` sibling, fsync'd, renamed;
2. the manifest (which embeds every shard's CRC32) is written the same
   way, *after* all shards;
3. the ``COMMIT`` marker — carrying the manifest's own CRC32 — is
   renamed into place last, then the step directory is fsync'd.

Discovery (:func:`completed_steps`) therefore never has to trust a
half-written checkpoint: a new-format directory without ``COMMIT`` is a
crashed save and is skipped; a directory without ``manifest.json`` and
without ``shards/`` is a *legacy* (orbax) checkpoint whose own
rename-at-end protocol already implies completeness.
"""

import json
import os
import re
import zlib
from typing import Any, Dict, List, Optional

#: manifest format tag; bump on incompatible layout changes
FORMAT = "hvd-tpu-ckpt-v1"

MANIFEST_NAME = "manifest.json"
COMMIT_NAME = "COMMIT"
SHARDS_DIR = "shards"

_STEP_RE = re.compile(r"^step_(\d+)$")

#: classification of a step directory
COMMITTED = "committed"     # new format, COMMIT marker present
PARTIAL = "partial"         # new format, crashed before COMMIT
LEGACY = "legacy"           # pre-manifest (orbax) checkpoint


class IntegrityError(RuntimeError):
    """A checkpoint failed verification: torn manifest, checksum
    mismatch, missing shard file. Distinct from FileNotFoundError (the
    step was never written) because the *caller's* remedy differs: an
    integrity failure is walk-back material, a missing step is a usage
    error."""


def step_dir(directory: str, step: int) -> str:
    return os.path.join(directory, f"step_{step:010d}")


def parse_step(name: str) -> Optional[int]:
    m = _STEP_RE.match(name)
    return int(m.group(1)) if m else None


def crc32(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def atomic_write_bytes(path: str, data: bytes, fsync: bool = True) -> None:
    """tmp + fsync + rename: readers see the old content or all of the
    new, never a torn write. The pid suffix keeps concurrent writers
    (two processes persisting the same replicated shard) from clobbering
    each other's temp file mid-write."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        # a failed write (ENOSPC, kill mid-write) must not strand the
        # temp file: long-lived jobs would accumulate one per attempt
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def fsync_dir(path: str) -> None:
    """Make a rename durable: fsync the containing directory (no-op on
    filesystems/platforms without directory fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def shard_filename(leaf_index: int, starts) -> str:
    """Deterministic shard file name from the shard's global offsets, so
    every process derives the same name for the same shard without
    coordination. Scalars / unsharded leaves get ``full``."""
    sig = "-".join(str(int(s)) for s in starts) if starts else "full"
    return f"{leaf_index:05d}.{sig}.bin"


# -- manifest ---------------------------------------------------------------

def write_manifest(path: str, manifest: Dict[str, Any]) -> int:
    """Atomically write ``manifest.json``; returns its CRC32 (embedded in
    the COMMIT marker so a torn manifest is detectable without parsing)."""
    data = json.dumps(manifest, indent=1, sort_keys=True).encode()
    atomic_write_bytes(os.path.join(path, MANIFEST_NAME), data)
    return crc32(data)


def read_manifest(path: str, verify_commit: bool = True) -> Dict[str, Any]:
    """Parse and verify a step directory's manifest.

    Raises :class:`IntegrityError` when the manifest is torn, fails the
    COMMIT marker's checksum, or carries an unknown format tag — and
    FileNotFoundError when there is no manifest at all (legacy dir)."""
    mpath = os.path.join(path, MANIFEST_NAME)
    with open(mpath, "rb") as f:
        data = f.read()
    if verify_commit:
        commit = read_commit(path)
        if commit is not None and commit.get("manifest_crc32") is not None \
                and commit["manifest_crc32"] != crc32(data):
            raise IntegrityError(
                f"manifest checksum mismatch under {path!r}: the COMMIT "
                f"marker does not vouch for this manifest")
    try:
        manifest = json.loads(data)
    except ValueError as e:
        raise IntegrityError(f"unparseable manifest under {path!r}") from e
    if manifest.get("format") != FORMAT:
        raise IntegrityError(
            f"unknown checkpoint format {manifest.get('format')!r} under "
            f"{path!r} (want {FORMAT!r})")
    return manifest


def write_commit(path: str, step: int, manifest_crc: int) -> None:
    """The point of no return: after this rename the step is discoverable."""
    data = json.dumps({"step": step, "manifest_crc32": manifest_crc}).encode()
    atomic_write_bytes(os.path.join(path, COMMIT_NAME), data)
    fsync_dir(path)


def read_commit(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(os.path.join(path, COMMIT_NAME), "rb") as f:
            return json.loads(f.read())
    except FileNotFoundError:
        return None
    except ValueError:
        # A torn COMMIT cannot happen under the rename protocol; treat it
        # as present-but-unverifiable rather than hiding the step.
        return {}


# -- discovery --------------------------------------------------------------

def classify(path: str) -> str:
    """COMMITTED / PARTIAL / LEGACY for one step directory."""
    entries = set()
    try:
        entries = set(os.listdir(path))
    except OSError:
        pass
    if COMMIT_NAME in entries:
        return COMMITTED
    if MANIFEST_NAME in entries or SHARDS_DIR in entries:
        return PARTIAL
    return LEGACY


def all_step_dirs(directory: str) -> List[int]:
    """Every step directory (any state), ascending."""
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    return sorted(s for name in names
                  if (s := parse_step(name)) is not None)


def completed_steps(directory: str) -> List[int]:
    """Step numbers safe to restore from, newest first. New-format dirs
    count only once COMMIT landed; legacy (orbax) dirs count as before —
    orbax's own tmp-dir rename protocol filters its crashed saves (the
    tmp names don't match the step pattern)."""
    out = [s for s in all_step_dirs(directory)
           if classify(step_dir(directory, s)) != PARTIAL]
    out.reverse()
    return out


def latest_step(directory: str) -> Optional[int]:
    steps = completed_steps(directory)
    return steps[0] if steps else None
