"""Retention policy and background garbage collection.

Two composable knobs (``HVD_TPU_CHECKPOINT_KEEP`` /
``HVD_TPU_CHECKPOINT_KEEP_PERIOD``):

* **keep-last-N** — the N newest completed steps survive;
* **keep-every-K** — steps divisible by K survive forever (the
  "milestone" archive a long job keeps for offline eval).

A step survives if *either* rule wants it; the newest completed step
always survives (a GC pass must never delete the thing a crash would
restore from). With neither knob set, GC is off and every step is kept —
the facade's historical behavior.

Deletion is crash-consistent by ordering: the ``COMMIT`` marker goes
first (atomically demoting the step to "partial", which discovery
already skips), then the rest of the tree. A GC pass killed halfway
leaves a partial dir that the next pass sweeps, never a
restorable-looking half-checkpoint.
"""

import logging
import os
import shutil
from typing import Iterable, List, Set

from . import layout

log = logging.getLogger("horovod_tpu.checkpointing")


def retained_steps(steps: Iterable[int], keep: int = 0,
                   keep_period: int = 0) -> Set[int]:
    """The subset of ``steps`` the policy preserves. No policy = keep all."""
    steps = sorted(set(steps))
    if not steps or (keep <= 0 and keep_period <= 0):
        return set(steps)
    out: Set[int] = {steps[-1]}
    if keep > 0:
        out.update(steps[-keep:])
    if keep_period > 0:
        out.update(s for s in steps if s % keep_period == 0)
    return out


def _delete_step(directory: str, step: int) -> None:
    path = layout.step_dir(directory, step)
    commit = os.path.join(path, layout.COMMIT_NAME)
    try:
        os.unlink(commit)           # demote to partial first
        layout.fsync_dir(path)
    except FileNotFoundError:
        pass                        # legacy or already-partial dir
    # no ignore_errors: a failed removal must reach collect()'s warning
    # path and stay OUT of the removed count — the step is already
    # demoted, so a later pass retries the sweep
    shutil.rmtree(path)


def collect(directory: str, keep: int = 0, keep_period: int = 0,
            fault_point=None) -> List[int]:
    """One GC pass; returns the steps it removed.

    Superseded completed steps outside the retained set go, and so do
    partial (crashed-save) dirs older than the newest completed step —
    they can never complete. Failures are logged, never raised: GC runs
    on the background writer and a full-disk ``rmtree`` hiccup must not
    poison an otherwise healthy save pipeline.
    """
    completed = layout.completed_steps(directory)    # newest first
    if not completed:
        return []
    if fault_point is not None:
        fault_point.fire()
    retain = retained_steps(completed, keep, keep_period)
    removed: List[int] = []
    newest = completed[0]
    for step in layout.all_step_dirs(directory):
        state = layout.classify(layout.step_dir(directory, step))
        if state == layout.PARTIAL:
            if step >= newest:
                continue            # possibly still being written
        elif step in retain:
            continue
        try:
            _delete_step(directory, step)
            removed.append(step)
        except OSError:
            log.warning("checkpoint gc: failed to remove step %d under %s",
                        step, directory, exc_info=True)
    if removed:
        log.info("checkpoint gc: removed %d superseded step(s) under %s: %s",
                 len(removed), directory, removed)
    return removed
