"""Async sharded checkpointing subsystem.

The CheckFreq/Orbax-style pattern grown from the old single-writer
synchronous ``horovod_tpu.checkpoint`` module (which remains as a thin
facade over this package):

* **snapshot-then-persist** — ``CheckpointManager.save(step, tree)``
  copies leaves to host on the training thread, a bounded background
  writer does the serialize/checksum/fsync/commit
  (:mod:`.manager`, :mod:`.snapshot`);
* **sharded multi-writer layout with integrity manifests** — each
  process writes only the shards it owns; a JSON manifest carries
  per-shard CRC32s and an atomically-renamed ``COMMIT`` marker gates
  discovery (:mod:`.layout`);
* **elastic resharding restore** — shards reassemble by global offsets
  and re-stage onto any target sharding, so the saved and restoring
  world sizes are independent;
* **retention GC** — keep-last-N / keep-every-K from the writer thread
  (:mod:`.gc`).

See docs/checkpoint.md for the full layout, commit protocol, knobs,
metrics, and chaos-drill recipes.
"""

from .gc import collect, retained_steps                          # noqa: F401
from .layout import (COMMITTED, LEGACY, PARTIAL, IntegrityError,  # noqa: F401
                     classify, completed_steps, latest_step, step_dir)
from .manager import (CheckpointCallback, CheckpointManager,      # noqa: F401
                      CheckpointWriterCrashed, drain_all)
from .snapshot import snapshot_tree                               # noqa: F401


def save(directory: str, step: int, tree, force: bool = False) -> str:
    """One-shot synchronous save (the facade's contract: returns after
    the step is committed; eager multi-process runs barrier)."""
    return CheckpointManager(directory).save(step, tree, async_=False,
                                             force=force)


def restore(directory: str, step=None, target=None, sharding=None,
            fallback: bool = False):
    """One-shot restore through a throwaway manager (see
    :meth:`CheckpointManager.restore`)."""
    return CheckpointManager(directory).restore(
        step=step, target=target, sharding=sharding, fallback=fallback)
