"""The checkpoint manager: snapshot-then-persist with a background writer.

``CheckpointManager.save(step, tree, async_=True)`` does the minimum on
the training thread — copy leaves to host memory (:mod:`.snapshot`) and
enqueue — and a single background writer thread does everything
expensive: serialize shards, checksum, fsync, write the manifest, land
the ``COMMIT`` marker, and run retention GC (:mod:`.gc`). The in-flight
queue is bounded (``HVD_TPU_CHECKPOINT_MAX_INFLIGHT``): a training loop
that outruns storage *blocks in save()* instead of buffering unbounded
host copies of the model.

Failure contract (CheckFreq/Orbax-style):

* writer errors never escape the writer thread at the moment they
  happen; they surface on the **next** ``save()`` or
  ``wait_until_finished()`` — the training loop learns that persistence
  is sick at a point where it can react;
* a save that dies mid-persist leaves a *partial* step directory (no
  ``COMMIT``), which discovery skips and GC eventually sweeps — restore
  can only ever land on a fully committed step;
* ``restore`` verifies every shard's CRC32 against the manifest before
  trusting it; with ``fallback=True`` an integrity failure walks back to
  the previous committed step
  (``hvd_tpu_checkpoint_integrity_failures_total`` +
  ``hvd_tpu_checkpoint_fallbacks_total`` account for the skip).

Chaos sites: ``checkpoint.write`` (per shard file), ``checkpoint.manifest``
(manifest + COMMIT), ``checkpoint.gc`` (each GC pass). A ``crash`` kind at
the write/manifest sites kills the *writer component* mid-persist (the
PR-3 launcher-crash pattern via ``FaultPoint.fire(crash=...)``) — the
abandoned step stays partial and the writer hot-restarts for the next
item, which is exactly the drill
``HVD_TPU_FAULT_SPEC='checkpoint.write:crash:once'`` replays
deterministically.
"""

import atexit
import json
import logging
import os
import queue
import shutil
import threading
import time
import weakref
from typing import Any, List, Optional

from .. import _locks
from .. import config as _config
from .. import faults as _faults
from .. import metrics as _metrics
from ..callbacks import Callback as _CallbackBase
from . import gc as _gc
from . import layout
from . import snapshot as _snapshot
from .layout import IntegrityError

log = logging.getLogger("horovod_tpu.checkpointing")

_M_SAVE_SECONDS = _metrics.histogram(
    "hvd_tpu_checkpoint_save_seconds",
    "Checkpoint save latency split by phase: 'snapshot' is the on-thread "
    "device->host copy (what an async save costs the training loop), "
    "'persist' is the background serialize+checksum+write+commit.",
    labels=("phase",))
_M_BYTES = _metrics.counter(
    "hvd_tpu_checkpoint_bytes_total",
    "Checkpoint payload bytes persisted by this process (shard files, "
    "pre-compression raw array bytes).")
_M_INFLIGHT = _metrics.gauge(
    "hvd_tpu_checkpoint_inflight",
    "Async checkpoint saves snapshotted but not yet committed (queued or "
    "being persisted). Bounded by HVD_TPU_CHECKPOINT_MAX_INFLIGHT.")
_M_GC_REMOVED = _metrics.counter(
    "hvd_tpu_checkpoint_gc_removed_total",
    "Checkpoint steps deleted by the retention GC "
    "(HVD_TPU_CHECKPOINT_KEEP / HVD_TPU_CHECKPOINT_KEEP_PERIOD).")
_M_INTEGRITY = _metrics.counter(
    "hvd_tpu_checkpoint_integrity_failures_total",
    "Checkpoint integrity verification failures: shard checksum mismatch, "
    "torn/unparseable manifest, missing shard file, uncommitted step.")
_M_FALLBACKS = _metrics.counter(
    "hvd_tpu_checkpoint_fallbacks_total",
    "restore(fallback=True) calls that skipped a corrupt/partial/missing "
    "selected step and restored an earlier completed step instead.")

#: storage-plane fault sites; error kind raises OSError (what a sick
#: filesystem looks like), crash kind kills the writer component
_FP_WRITE = _faults.FaultPoint("checkpoint.write", exc=OSError)
_FP_MANIFEST = _faults.FaultPoint("checkpoint.manifest", exc=OSError)
_FP_GC = _faults.FaultPoint("checkpoint.gc", exc=OSError)


class CheckpointWriterCrashed(RuntimeError):
    """An injected ``crash`` fault killed the background writer
    mid-persist. The step being written is abandoned (partial, never
    discoverable); the writer hot-restarts for the next item."""


def _writer_crash() -> None:
    raise CheckpointWriterCrashed(
        "checkpoint writer killed mid-persist (injected crash)")


#: live managers, for end-of-life drains (elastic reset must not re-exec
#: the process image while a committed-looking save is still in flight)
_MANAGERS: "weakref.WeakSet[CheckpointManager]" = weakref.WeakSet()


def drain_all() -> None:
    """Drain every live manager's in-flight saves (best-effort). Called
    from ``on_train_end`` paths and the elastic reset, so the final
    epoch's checkpoint lands before the process image goes away."""
    for mgr in list(_MANAGERS):
        try:
            mgr.wait_until_finished()
        except Exception:   # noqa: BLE001 — draining is best-effort
            log.warning("checkpoint: error surfaced while draining %r",
                        mgr.directory, exc_info=True)


# The writer is a daemon thread (a hung filesystem must not block
# interpreter exit forever), so a script that never calls
# wait_until_finished() would silently abandon its last async saves at
# teardown — drain at exit, best-effort, before daemon threads die.
atexit.register(drain_all)

_STOP = object()


class _Pending:
    __slots__ = ("step", "snap", "force", "path")

    def __init__(self, step: int, snap, force: bool, path: str):
        self.step = step
        self.snap = snap
        self.force = force
        self.path = path


#: shared live-world knob lookup (config.live_config); kept under the
#: old private name for this module's existing call sites
_live_config = _config.live_config


def _process_count() -> int:
    try:
        import jax
        return jax.process_count()
    except Exception:   # noqa: BLE001 — uninitialized backend
        return 1


def _process_index() -> int:
    try:
        import jax
        return jax.process_index()
    except Exception:   # noqa: BLE001
        return 0


class CheckpointManager:
    """Async sharded checkpointing for one checkpoint root directory.

    Thread-safety: ``save``/``wait_until_finished``/``restore`` are meant
    to be called from the training thread; the background writer is
    internal. One manager per directory — two managers GC'ing the same
    root would race.
    """

    def __init__(self, directory: str, keep: Optional[int] = None,
                 keep_period: Optional[int] = None,
                 max_inflight: Optional[int] = None):
        cfg = _live_config()
        self.directory = directory
        self.keep = int(cfg.get(_config.CHECKPOINT_KEEP)
                        if keep is None else keep)
        self.keep_period = int(cfg.get(_config.CHECKPOINT_KEEP_PERIOD)
                               if keep_period is None else keep_period)
        self.max_inflight = max(1, int(
            cfg.get(_config.CHECKPOINT_MAX_INFLIGHT)
            if max_inflight is None else max_inflight))
        self._queue: "queue.Queue" = queue.Queue(maxsize=self.max_inflight)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._lock = _locks.lock("checkpointing.CheckpointManager._lock")
        self._pending_steps: set = set()
        #: newest step the SDC policy confirmed clean (docs/robustness.md,
        #: SDC section); None until promote_last_good() is called
        self.last_good_step: Optional[int] = None
        _MANAGERS.add(self)

    # -- world plumbing ------------------------------------------------------

    def _world_size(self) -> int:
        from .. import basics
        return basics.size() if basics.is_initialized() else 1

    def _is_writer(self) -> bool:
        """Multi-host jax: every process writes its own shards. Eager
        multi-process (independent single-device jax runtimes): rank-0
        convention, like the reference's examples."""
        from .. import basics
        if _process_count() > 1:
            return True
        return not basics.is_initialized() or basics.rank() == 0

    def _barrier(self) -> None:
        from .. import basics
        if basics.is_initialized() and basics.size() > 1 \
                and _process_count() == 1:
            from ..collectives import barrier
            barrier()

    # -- error surfacing -----------------------------------------------------

    def _raise_pending(self) -> None:
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise err

    def _record_error(self, err: BaseException) -> None:
        log.error("checkpoint writer failed: %s", err, exc_info=err)
        with self._lock:
            if self._error is None:     # first error wins; later ones logged
                self._error = err

    # -- save ----------------------------------------------------------------

    def save(self, step: int, tree: Any, async_: bool = True,
             force: bool = False) -> str:
        """Checkpoint ``tree`` for ``step``; returns the step path.

        ``async_=True`` (default): returns after the host snapshot; the
        write happens on the background thread and any failure surfaces
        on the next ``save()``/``wait_until_finished()``. ``async_=False``
        persists before returning (and, in eager multi-process runs,
        barriers so non-root ranks can't race past an unfinished write —
        the facade's historical contract).
        """
        self._raise_pending()
        path = layout.step_dir(self.directory, step)
        # overwrite guard covers committed AND legacy (orbax) dirs — the
        # old facade raised on an existing step too — plus steps still
        # queued for the writer (on disk the duplicate isn't visible
        # yet); only a crashed-save partial is silently overwritable
        with self._lock:
            dup_pending = step in self._pending_steps
        if not force and (dup_pending or (
                os.path.isdir(path)
                and layout.classify(path) != layout.PARTIAL)):
            raise FileExistsError(
                f"checkpoint step {step} already exists under "
                f"{self.directory!r} (pass force=True to overwrite)")
        if not self._is_writer():
            if not async_:
                self._barrier()
            return path
        t0 = time.perf_counter()
        snap = _snapshot.snapshot_tree(tree, world_size=self._world_size())
        _M_SAVE_SECONDS.labels(phase="snapshot").observe(
            time.perf_counter() - t0)
        pending = _Pending(step, snap, force, path)
        if async_:
            _M_INFLIGHT.inc()
            with self._lock:
                self._pending_steps.add(step)
            try:
                self._ensure_writer()
                self._queue.put(pending)    # blocks when full: backpressure
            except BaseException:
                _M_INFLIGHT.dec()
                with self._lock:
                    self._pending_steps.discard(step)
                raise
        else:
            # drain first: _persist (and its GC pass) must stay
            # single-threaded per manager, or a sync save's GC could
            # sweep a partial step the background writer is mid-writing
            self._queue.join()
            self._raise_pending()
            self._persist(pending)
            self._barrier()
            if _process_count() > 1 and _process_index() != 0:
                # multi-host sync semantics: "save returned" must mean
                # "step committed" on every process, and only process 0
                # writes the COMMIT — wait for it (no data-plane
                # collective here; the runtime may be mid-teardown)
                self._await_commit(path, step)
        return path

    def _await_commit(self, path: str, step: int) -> None:
        deadline = time.monotonic() + float(
            _live_config().get(_config.INIT_TIMEOUT_SECONDS))
        while layout.classify(path) != layout.COMMITTED:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"step {step} under {self.directory!r} was not "
                    f"committed by process 0 before the deadline")
            time.sleep(0.05)

    def wait_until_finished(self) -> None:
        """Drain every queued/in-progress save, then surface any writer
        error recorded since the last drain."""
        self._queue.join()
        self._raise_pending()

    def drain_for_preemption(self, step: Optional[int] = None,
                             tree: Any = None) -> Optional[int]:
        """Preemption-notice drain: finish every in-flight save, then —
        when the caller supplies its current ``(step, tree)`` and the
        newest committed step is older — force one final *synchronous*
        save, so the grace window is spent persisting progress instead of
        re-running it after the handoff. A save already in flight (or
        committed) for ``step`` is drained, never duplicated: the
        in-flight copy lands via ``wait_until_finished`` and the stale
        check then sees it committed. Returns the newest committed step
        (None when the directory holds none).

        Under sharded (fsdp) training this is the *shard handoff* of a
        graceful drain: the departing host persists its own parameter
        shards here, and the surviving mesh's restore plan reassembles
        them from the checkpoint by recorded global offsets — peers are
        never asked to serve shards they do not hold."""
        self.wait_until_finished()
        if step is not None and tree is not None:
            latest = self.latest_step()
            if latest is None or latest < step:
                try:
                    self.save(step, tree, async_=False)
                except FileExistsError:
                    # landed between the check and the save (another
                    # writer/process): already durable, nothing to do
                    pass
        return self.latest_step()

    def close(self) -> None:
        """Drain and stop the writer thread (managers are reusable after
        close — the next async save restarts the writer)."""
        # take the handle under the lock so close() can't race
        # _ensure_writer replacing self._thread; the blocking put/join
        # happen after the lock is released (the writer's finally block
        # needs this lock to make progress, so a blocking put here while
        # holding it could deadlock on a full queue)
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None and thread.is_alive():
            self._queue.put(_STOP)
            while True:
                thread.join(timeout=0.1)
                if not thread.is_alive():
                    break
                # a save() racing this close() may have started a fresh
                # writer that consumed our sentinel — re-send it so the
                # thread we are joining is guaranteed to see one (a
                # leftover sentinel merely stops a later writer early;
                # _ensure_writer restarts it on the next async save)
                try:
                    self._queue.put_nowait(_STOP)
                except queue.Full:
                    pass
        self._raise_pending()

    # -- background writer ---------------------------------------------------

    def _ensure_writer(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._writer_loop, daemon=True,
                    name="hvd-tpu-ckpt-writer")
                self._thread.start()

    def _writer_loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is _STOP:
                self._queue.task_done()
                return
            try:
                self._persist(item)
            except BaseException as e:  # noqa: BLE001 — surfaced on next save
                # A crash fault "kills" the writer component: the step
                # stays partial (no cleanup — a real dead writer cleans
                # nothing) and the loop hot-restarts for the next item.
                self._record_error(e)
            finally:
                _M_INFLIGHT.dec()
                with self._lock:
                    self._pending_steps.discard(item.step)
                self._queue.task_done()

    def _persist(self, pending: "_Pending") -> None:
        t0 = time.perf_counter()
        path = pending.path
        snap = pending.snap
        multihost = _process_count() > 1
        reused_dir = os.path.exists(path)
        if reused_dir and not multihost:
            # force re-save or a stale partial from a crashed attempt;
            # multi-host writers share the dir and must not sweep it
            shutil.rmtree(path)
        shards_dir = os.path.join(path, layout.SHARDS_DIR)
        os.makedirs(shards_dir, exist_ok=True)
        if multihost:
            # Re-saving into a shared step dir: demote the step FIRST (a
            # stale COMMIT must never vouch for a mix of old and new
            # shard bytes mid-rewrite) and drop this process's stale
            # shard table so the merge can't consume a previous
            # attempt's checksums.
            for stale in (os.path.join(path, layout.COMMIT_NAME),
                          os.path.join(shards_dir,
                                       f"index.{_process_index()}.json")):
                try:
                    os.unlink(stale)
                except FileNotFoundError:
                    pass
            layout.fsync_dir(path)
        leaf_entries = []
        written = 0
        for leaf in snap.leaves:
            if leaf.local and multihost and _process_index() != 0:
                # leaves with no jax-level ownership (python objects,
                # plain numpy arrays every process holds in full): the
                # rank-0 convention wins — N processes renaming
                # possibly-different bytes onto one file would race
                continue
            entry = {"index": leaf.index, "path": leaf.path,
                     "kind": leaf.kind}
            shard_entries = []
            if leaf.kind == _snapshot.OBJECT:
                fname = f"{leaf.index:05d}.obj.bin"
                shard_entries.append(
                    self._write_shard(shards_dir, fname, leaf.payload))
            else:
                entry["dtype"] = leaf.dtype
                entry["shape"] = list(leaf.shape)
                for shard in leaf.shards:
                    fname = layout.shard_filename(leaf.index, shard.starts)
                    shard_entries.append(self._write_shard(
                        shards_dir, fname, shard.data.tobytes(),
                        starts=list(shard.starts),
                        shape=list(shard.data.shape)))
            written += sum(e["nbytes"] for e in shard_entries)
            entry["shards"] = shard_entries
            leaf_entries.append(entry)
        _M_BYTES.inc(written)
        if multihost:
            self._write_process_index(path, leaf_entries)
            if _process_index() != 0:
                _M_SAVE_SECONDS.labels(phase="persist").observe(
                    time.perf_counter() - t0)
                return
            leaf_entries = self._merge_process_indexes(
                path, snap, verify_bytes=reused_dir)
        _FP_MANIFEST.fire(crash=_writer_crash)
        manifest = {
            "format": layout.FORMAT,
            "step": pending.step,
            "world_size": snap.world_size,
            "process_count": _process_count(),
            "treedef": _snapshot.encode_treedef(snap.treedef_blob),
            "leaves": leaf_entries,
        }
        crc = layout.write_manifest(path, manifest)
        layout.write_commit(path, pending.step, crc)
        _M_SAVE_SECONDS.labels(phase="persist").observe(
            time.perf_counter() - t0)
        log.info("checkpoint: committed step %d under %s (%d bytes)",
                 pending.step, self.directory, written)
        self._collect_garbage()

    def _write_shard(self, shards_dir: str, fname: str, data: bytes,
                     **extra) -> dict:
        _FP_WRITE.fire(crash=_writer_crash)
        layout.atomic_write_bytes(os.path.join(shards_dir, fname), data)
        entry = {"file": f"{layout.SHARDS_DIR}/{fname}",
                 "crc32": layout.crc32(data), "nbytes": len(data)}
        entry.update(extra)
        return entry

    # -- multi-host manifest merge (shared-filesystem protocol) --------------

    def _write_process_index(self, path: str, leaf_entries: List[dict]
                             ) -> None:
        """Each process publishes its shard table atomically; process 0
        assembles the manifest once every table landed — commit ordering
        without a collective (the data plane may be mid-teardown)."""
        layout.atomic_write_bytes(
            os.path.join(path, layout.SHARDS_DIR,
                         f"index.{_process_index()}.json"),
            json.dumps(leaf_entries).encode())

    def _merge_process_indexes(self, path: str, snap,
                               verify_bytes: bool = False) -> List[dict]:
        count = _process_count()
        deadline = time.monotonic() + float(
            _live_config().get(_config.INIT_TIMEOUT_SECONDS))
        merged = {leaf.index: {"index": leaf.index, "path": leaf.path,
                               "kind": leaf.kind, "shards": []}
                  for leaf in snap.leaves}
        for leaf in snap.leaves:
            if leaf.kind == _snapshot.ARRAY:
                merged[leaf.index]["dtype"] = leaf.dtype
                merged[leaf.index]["shape"] = list(leaf.shape)
        for proc in range(count):
            ipath = os.path.join(path, layout.SHARDS_DIR,
                                 f"index.{proc}.json")
            for entry in self._fresh_index(path, ipath, deadline,
                                           verify_bytes):
                merged[entry["index"]]["shards"].extend(entry["shards"])
        for entry in merged.values():
            entry["shards"].sort(key=lambda s: s["file"])
        return [merged[i] for i in sorted(merged)]

    def _fresh_index(self, path: str, ipath: str, deadline: float,
                     verify_bytes: bool) -> List[dict]:
        """Wait for a peer's shard table. Peers rename every shard into
        place *before* atomically writing their index, so in a fresh
        step directory index-present implies shards-complete and the
        table is trusted as-is. Only a *reused* directory (force
        re-save / retry after a crashed attempt) can hold a stale index
        from the previous attempt — there, ``verify_bytes`` checks every
        referenced shard's checksum against the bytes on disk and
        re-polls until the fresh table lands, so the manifest can never
        be committed against a mix of attempts (worth the extra
        read-back I/O, which the common path never pays)."""
        while True:
            entries = None
            if os.path.exists(ipath):
                try:
                    with open(ipath, "rb") as f:
                        entries = json.loads(f.read())
                except (OSError, ValueError):
                    entries = None
            if entries is not None and (not verify_bytes or all(
                    self._shard_on_disk_matches(path, s)
                    for e in entries for s in e["shards"])):
                return entries
            if time.monotonic() > deadline:
                raise IntegrityError(
                    f"no consistent shard index at {ipath!r} before the "
                    f"merge deadline")
            time.sleep(0.05)

    @staticmethod
    def _shard_on_disk_matches(path: str, shard: dict) -> bool:
        try:
            with open(os.path.join(path, shard["file"]), "rb") as f:
                data = f.read()
        except OSError:
            return False
        return layout.crc32(data) == shard["crc32"]

    # -- retention GC --------------------------------------------------------

    def _collect_garbage(self) -> None:
        if self.keep <= 0 and self.keep_period <= 0:
            return
        if _process_count() > 1 and _process_index() != 0:
            return      # one collector per job
        try:
            removed = _gc.collect(self.directory, self.keep,
                                  self.keep_period, fault_point=_FP_GC)
        except Exception:   # noqa: BLE001 — GC must not poison saves
            log.warning("checkpoint gc pass failed under %s",
                        self.directory, exc_info=True)
            return
        if removed:
            _M_GC_REMOVED.inc(len(removed))

    # -- restore -------------------------------------------------------------

    def restore(self, step: Optional[int] = None, target: Any = None,
                sharding=None, fallback: bool = False) -> Any:
        """Restore the pytree at ``step`` (default: latest committed).

        ``sharding`` re-stages leaves onto a target mesh/sharding — the
        elastic resume-onto-a-different-world-size case: shards are
        reassembled by their recorded global offsets, so the saved and
        restoring world sizes are independent. ``fallback=True`` walks
        back past corrupt/partial/missing steps (counted); without it the
        first failure surfaces.
        """
        if step is None:
            candidates = layout.completed_steps(self.directory)
            if not candidates:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory!r}")
        elif fallback:
            candidates = [s for s in layout.completed_steps(self.directory)
                          if s <= step]
            if not candidates:
                raise FileNotFoundError(
                    f"no checkpoints at or before step {step} under "
                    f"{self.directory!r}")
        else:
            # The requested step must at least exist on disk; orbax (and
            # the shard reader) would otherwise surface an internal error
            # for what is a plain usage mistake.
            if not os.path.isdir(layout.step_dir(self.directory, step)):
                raise FileNotFoundError(
                    f"no checkpoint for step {step} under "
                    f"{self.directory!r}")
            candidates = [step]
        if not fallback:
            candidates = candidates[:1]
        fell_back = step is not None and fallback and candidates[0] != step
        if fell_back:
            log.warning(
                "checkpoint: step %d does not exist under %s; falling back "
                "to step %d", step, self.directory, candidates[0])
            if layout.classify(layout.step_dir(self.directory, step)) \
                    == layout.PARTIAL:
                # the requested step is a crashed save (no COMMIT) —
                # that's an integrity event, not a never-written step
                _M_INTEGRITY.inc()
        for i, cand in enumerate(candidates):
            try:
                tree = self._restore_step(cand, target)
            except Exception as e:  # noqa: BLE001 — legacy path raises orbax
                if isinstance(e, IntegrityError):
                    _M_INTEGRITY.inc()
                if i + 1 >= len(candidates):
                    raise
                log.warning(
                    "checkpoint: step %d under %s is corrupt or partial "
                    "(%s); falling back to step %d", cand, self.directory,
                    e, candidates[i + 1])
                if isinstance(e, IntegrityError):
                    # checksum-proven corruption: demote the step so
                    # discovery/GC stop counting it — otherwise a resumed
                    # run's fresh commits rank below the stale corrupt
                    # steps and retention GC deletes new progress while
                    # protecting garbage
                    self._demote(cand)
                fell_back = True
                continue
            if fell_back:
                _M_FALLBACKS.inc()
            if fallback:
                # One summary line on EVERY fallback restore that did not
                # land on the newest step directory — including the quiet
                # case where newer steps are PARTIAL (crashed saves) and
                # so never even entered `candidates`. Operators must be
                # able to see from the log alone that progress was lost.
                skipped = [s for s in layout.all_step_dirs(self.directory)
                           if s > cand]
                if skipped:
                    log.warning(
                        "checkpoint: restored step %d from %s; skipped "
                        "newer step(s) %s (partial or corrupt)", cand,
                        self.directory,
                        ", ".join(str(s) for s in skipped))
            if sharding is not None:
                import jax
                tree = jax.device_put(tree, sharding)
            return tree

    # -- last-good (SDC rollback target) -------------------------------------

    def promote_last_good(self, step: int) -> None:
        """Mark ``step`` as the newest checkpoint that survived the SDC
        guard for HVD_TPU_SDC_CONFIRM_STEPS subsequent steps — the only
        step ``restore_last_good`` will consider newest-first from."""
        self.last_good_step = int(step)

    def restore_last_good(self, target: Any = None, sharding=None) -> Any:
        """Restore the last-good step (``restore`` with fallback past
        anything that rotted on disk since the promotion). Raises
        RuntimeError when nothing was ever promoted — rollback without a
        confirmed-good target would just reload suspect state."""
        if self.last_good_step is None:
            raise RuntimeError(
                "no last-good checkpoint promoted yet; cannot roll back "
                f"under {self.directory!r}")
        return self.restore(step=self.last_good_step, target=target,
                            sharding=sharding, fallback=True)

    def _demote(self, step: int) -> None:
        """Atomically un-commit a corrupt step (idempotent across
        processes); the partial dir left behind is swept by GC."""
        path = layout.step_dir(self.directory, step)
        try:
            os.unlink(os.path.join(path, layout.COMMIT_NAME))
            layout.fsync_dir(path)
            log.warning("checkpoint: demoted corrupt step %d under %s "
                        "(COMMIT removed)", step, self.directory)
        except OSError:
            pass        # legacy dir, already demoted, or read-only fs

    def _restore_step(self, step: int, target: Any = None) -> Any:
        path = layout.step_dir(self.directory, step)
        state = layout.classify(path)
        if state == layout.PARTIAL:
            raise IntegrityError(
                f"step {step} under {self.directory!r} was never committed "
                f"(crashed save)")
        if state == layout.LEGACY:
            import orbax.checkpoint as ocp
            return ocp.PyTreeCheckpointer().restore(path, item=target)
        manifest = layout.read_manifest(path)

        def read_shard(entry: dict) -> bytes:
            fpath = os.path.join(path, entry["file"])
            try:
                with open(fpath, "rb") as f:
                    data = f.read()
            except FileNotFoundError as e:
                raise IntegrityError(
                    f"manifest references missing shard {entry['file']!r} "
                    f"under {path!r}") from e
            if layout.crc32(data) != entry["crc32"]:
                raise IntegrityError(
                    f"checksum mismatch for shard {entry['file']!r} under "
                    f"{path!r}")
            return data

        leaves = []
        for leaf_m in manifest["leaves"]:
            if leaf_m["kind"] == _snapshot.OBJECT:
                leaves.append(_snapshot.assemble_object(
                    read_shard(leaf_m["shards"][0])))
            else:
                leaves.append(_snapshot.assemble_array(leaf_m, read_shard))
        import jax
        if target is not None:
            # honor the facade's "target provides structure" contract:
            # rebuild with the caller's treedef (also the escape hatch
            # when the saved treedef's custom node classes moved module)
            t_flat, t_def = jax.tree_util.tree_flatten(target)
            if len(t_flat) != len(leaves):
                raise IntegrityError(
                    f"target structure has {len(t_flat)} leaves, "
                    f"checkpoint step {step} has {len(leaves)}")
            return jax.tree_util.tree_unflatten(t_def, leaves)
        treedef = _snapshot.decode_treedef(manifest["treedef"])
        return jax.tree_util.tree_unflatten(treedef, leaves)

    # -- discovery -----------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return layout.latest_step(self.directory)

    def all_steps(self) -> List[int]:
        """Committed/restorable steps, newest first."""
        return layout.completed_steps(self.directory)


class CheckpointCallback(_CallbackBase):
    """Save ``run.params`` every ``epochs_per_save`` epochs through a
    :class:`CheckpointManager` (rank-0 convention of the reference
    examples).

    ``async_=True`` overlaps persistence with the next epoch; the
    in-flight saves are drained in ``on_train_end`` (and by the elastic
    reset via :func:`drain_all`), so the final epoch's checkpoint is
    never lost to process teardown. Each save records its step in
    ``logs["checkpoint_step"]``.
    """

    def __init__(self, directory: str, epochs_per_save: int = 1,
                 force: bool = True, async_: bool = False,
                 keep: Optional[int] = None,
                 keep_period: Optional[int] = None,
                 max_inflight: Optional[int] = None,
                 manager: Optional[CheckpointManager] = None):
        self.directory = directory
        self.epochs_per_save = epochs_per_save
        # force=True: an elastic resume re-saves epochs that already exist
        # on disk; refusing to overwrite would kill the resumed run
        self.force = force
        self.async_ = async_
        self.manager = manager or CheckpointManager(
            directory, keep=keep, keep_period=keep_period,
            max_inflight=max_inflight)
        self._last_saved: Optional[int] = None

    def on_epoch_end(self, epoch, logs=None):
        if (epoch + 1) % self.epochs_per_save == 0:
            self.manager.save(epoch, self.run.params, async_=self.async_,
                              force=self.force)
            self._last_saved = epoch
            if logs is not None:
                logs["checkpoint_step"] = epoch

    def on_train_end(self, logs=None):
        self.manager.wait_until_finished()
        if logs is not None and self._last_saved is not None:
            logs["checkpoint_step"] = self._last_saved
