"""Host snapshots of pytrees, and their reassembly.

The training-thread half of snapshot-then-persist: :func:`snapshot_tree`
copies every leaf off the devices (``jax.Array`` -> host numpy, one copy)
and records, per leaf, the *global* shape plus the shards this process
owns. It does no file I/O, no checksumming, no serialization of array
bytes — those are the background writer's job — so the training loop
pays device-transfer cost only.

Shard ownership follows jax's addressable-shard model: a process owns
the shards of its local devices whose ``replica_id`` is 0, so replicated
leaves are written exactly once across the job and an N-way sharded leaf
is written as N independent files by whoever holds each piece. On a
single process (the eager path) that degenerates to "rank 0 writes
everything", matching the reference's rank-0 convention.

Reassembly (:func:`assemble_array`) is the inverse and is deliberately
world-size-agnostic: it pastes shards into a full host array by their
recorded offsets, which is what makes restoring a world-size-4
checkpoint onto 2 processes (or 1) a plain read — resharding happens
afterwards via ``jax.device_put`` onto the *target* sharding.
"""

import base64
import pickle
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .layout import IntegrityError

#: leaf kinds in the manifest
ARRAY = "array"
OBJECT = "object"


class HostShard:
    """One contiguous piece of a leaf this process owns, already on host."""

    __slots__ = ("starts", "data")

    def __init__(self, starts: Tuple[int, ...], data: np.ndarray):
        self.starts = starts
        self.data = data


class LeafSnapshot:
    """Host copy of one pytree leaf plus its global layout.

    ``local=True`` marks leaves every process holds in full with no
    jax-level ownership information (plain numpy arrays, python
    objects): in a multi-host save only process 0 writes them — N
    processes renaming possibly-different bytes onto one shard file
    would race. jax.Array leaves carry real ownership (addressable
    shards + replica ids) and are written by whoever owns each piece.
    """

    __slots__ = ("index", "path", "kind", "dtype", "shape", "shards",
                 "payload", "local")

    def __init__(self, index: int, path: str, kind: str,
                 dtype: Optional[str] = None,
                 shape: Optional[Tuple[int, ...]] = None,
                 shards: Optional[List[HostShard]] = None,
                 payload: Optional[bytes] = None, local: bool = True):
        self.index = index
        self.path = path
        self.kind = kind
        self.dtype = dtype
        self.shape = shape
        self.shards = shards or []
        self.payload = payload      # OBJECT leaves: pickled bytes
        self.local = local

    def nbytes(self) -> int:
        if self.kind == OBJECT:
            return len(self.payload or b"")
        return sum(s.data.nbytes for s in self.shards)


class TreeSnapshot:
    """Everything save() captured on the training thread."""

    __slots__ = ("treedef_blob", "leaves", "world_size")

    def __init__(self, treedef_blob: bytes, leaves: List[LeafSnapshot],
                 world_size: int):
        self.treedef_blob = treedef_blob
        self.leaves = leaves
        self.world_size = world_size

    def nbytes(self) -> int:
        return sum(leaf.nbytes() for leaf in self.leaves)


def _shard_starts(index, ndim: int) -> Tuple[int, ...]:
    """Global start offsets from a shard's index (tuple of slices)."""
    if not index:
        return ()
    starts = []
    for s in index[:ndim]:
        starts.append(int(s.start) if s.start is not None else 0)
    return tuple(starts)


def _snapshot_array_leaf(index: int, path: str, leaf) -> LeafSnapshot:
    import jax

    if isinstance(leaf, jax.Array):
        shards = []
        for shard in leaf.addressable_shards:
            if shard.replica_id != 0:
                continue        # replicated piece owned elsewhere
            # np.array (owned copy), NOT np.asarray: on the CPU backend
            # device_get can alias the device buffer, and a donated
            # buffer overwritten by the next jitted step would corrupt
            # the snapshot while it waits in the writer queue
            shards.append(HostShard(
                _shard_starts(shard.index, leaf.ndim),
                np.array(jax.device_get(shard.data))))
        # A fully-addressable jax.Array (single process, or sharded over
        # a purely host-local mesh) has no cross-process ownership: every
        # process that holds one holds it in full, exactly like a plain
        # numpy leaf — so the multihost rank-0 write convention applies.
        # Without this, N eager-dp processes each checkpointing their
        # bit-identical local-mesh replica would merge N overlapping
        # shard sets into one manifest and the restore-side coverage
        # check would (rightly) refuse it. Partially-addressable arrays
        # keep real per-process ownership via replica_id filtering.
        return LeafSnapshot(index, path, ARRAY, dtype=str(leaf.dtype),
                            shape=tuple(leaf.shape), shards=shards,
                            local=bool(getattr(leaf, "is_fully_addressable",
                                               False)))
    arr = np.array(leaf)    # copy: the caller may mutate after save()
    return LeafSnapshot(index, path, ARRAY, dtype=str(arr.dtype),
                        shape=tuple(arr.shape),
                        shards=[HostShard((0,) * arr.ndim, arr)])


def snapshot_tree(tree: Any, world_size: int = 1) -> TreeSnapshot:
    """Flatten ``tree`` and copy every leaf to host memory (the
    synchronous, on-thread part of an async save)."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    leaves: List[LeafSnapshot] = []
    for i, (keypath, leaf) in enumerate(flat):
        path = jax.tree_util.keystr(keypath)
        if isinstance(leaf, (jax.Array, np.ndarray, np.generic)):
            leaves.append(_snapshot_array_leaf(i, path, leaf))
        else:
            # non-array leaves (step counters, strings, optax schedule
            # state) round-trip through pickle with their exact types
            leaves.append(LeafSnapshot(
                i, path, OBJECT, payload=pickle.dumps(leaf)))
    return TreeSnapshot(pickle.dumps(treedef), leaves, world_size)


# -- manifest <-> snapshot glue --------------------------------------------

def encode_treedef(blob: bytes) -> str:
    return base64.b64encode(blob).decode("ascii")


def decode_treedef(text: str):
    return pickle.loads(base64.b64decode(text.encode("ascii")))


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # low-precision accelerator dtypes (bfloat16, float8_*) register
        # through ml_dtypes, which jax always ships
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def assemble_array(leaf_manifest: Dict[str, Any],
                   read_shard: Callable[[Dict[str, Any]], bytes]
                   ) -> np.ndarray:
    """Reassemble one ARRAY leaf from its manifest entry.

    ``read_shard(shard_entry) -> bytes`` is provided by the caller (which
    owns checksum verification and fault accounting). Raises
    :class:`IntegrityError` when the pasted shards do not exactly cover
    the leaf — a manifest that lies about coverage must not yield a
    silently half-initialized array.
    """
    dtype = _np_dtype(leaf_manifest["dtype"])
    shape = tuple(leaf_manifest["shape"])
    out = np.empty(shape, dtype=dtype)
    covered = 0
    for shard in leaf_manifest["shards"]:
        data = read_shard(shard)
        piece = np.frombuffer(data, dtype=dtype)
        sshape = tuple(shard["shape"])
        if piece.size != int(np.prod(sshape, dtype=np.int64)):
            raise IntegrityError(
                f"shard {shard.get('file')!r} of leaf "
                f"{leaf_manifest.get('path')!r}: payload holds {piece.size} "
                f"elements, manifest says shape {sshape}")
        piece = piece.reshape(sshape)
        starts = tuple(shard.get("starts") or ())
        if not shape:               # 0-d leaf
            out[()] = piece[()] if piece.shape == () else piece.ravel()[0]
        else:
            sel = tuple(slice(b, b + n) for b, n in zip(starts, sshape))
            out[sel] = piece
        covered += piece.size
    if covered != out.size:
        raise IntegrityError(
            f"leaf {leaf_manifest.get('path')!r}: shards cover {covered} "
            f"of {out.size} elements")
    return out


def assemble_object(payload: bytes) -> Any:
    return pickle.loads(payload)
