"""Metrics & telemetry for horovod_tpu: the third observability pillar.

The reference ships two observability surfaces — the chrome-tracing Timeline
(timeline.{h,cc}) and the StallInspector (stall_inspector.{h,cc}) — both
reproduced here (timeline.py, stall.py). What it never built is the one
production operation actually runs on: always-on, low-overhead **metrics**
(op counts, bytes moved, latency distributions, cache efficiency, queue
depths, stall and elastic events) that an operator can scrape, diff across
ranks, and alert on without turning on a trace.

This module is that pillar:

* a thread-safe registry of **counters**, **gauges** and fixed-bucket
  **histograms**, instrumented throughout the collective path
  (collectives.py, response_cache.py, stall.py, elastic/driver.py,
  optimizer.py, timeline.py — the observability layer observes itself);
* cells are native-backed (csrc/metrics.cc lock-free atomics) when the
  native runtime is built, with a pure-Python mutex fallback, so the hot
  path pays one atomic add whether or not anything ever scrapes;
* three read paths:
  1. :func:`snapshot` (exported as ``hvd.metrics_snapshot()``) — a plain
     dict of every series, deterministic key order;
  2. a Prometheus text-format HTTP endpoint (``GET /metrics``), enabled
     with ``HVD_TPU_METRICS_PORT`` (rank 0 by default,
     ``HVD_TPU_METRICS_ALL_RANKS=1`` for every process);
  3. :func:`metrics_allgather_summary` — an on-demand cross-rank
     allgather of each rank's snapshot, so per-rank skew (one rank's
     latency tail, a cache-miss storm) is visible from the coordinator.

Series follow Prometheus conventions (``_total`` counters, base-unit
names, ``le``-bucketed cumulative histograms). The registry is process-
global and survives ``hvd.shutdown()``/``hvd.init()`` cycles — an elastic
reset does not zero the operator's counters.
"""

import json
import threading
from typing import Dict, Iterable, Optional, Sequence, Tuple

from . import _locks
from . import config as _config
from ._native import get as _native_get

#: Default latency buckets in seconds: 100us .. 10s, roughly
#: logarithmic — eager dispatches sit in the middle, compile storms and
#: stalled peers land in the tail (Prometheus client default buckets).
DEFAULT_LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _fmt(v: float) -> str:
    """Prometheus number formatting: integral values without the '.0'."""
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


class _Cell:
    """One scalar sample (counter or gauge). Native-backed atomic double
    when built; otherwise a float under a mutex. ``inc``/``set`` are the
    instrumented hot path — one ctypes call or one lock/add.

    The native backing resolves LAZILY on first use, not at construction:
    subsystems register families at module import, and ``import
    horovod_tpu`` must never trigger the synchronous native build (the
    package's lazy-import contract). First use is in practice ``init()``
    — the same moment the stall inspector and response cache resolved
    native before metrics existed."""

    __slots__ = ("_nat", "_h", "_ready", "_lock", "_v")

    def __init__(self):
        self._ready = False
        self._nat = None
        self._h = None
        self._lock = _locks.lock("metrics._Cell._lock")
        self._v = 0.0

    def _resolve(self) -> None:
        with self._lock:
            if not self._ready:
                self._nat = _native_get()
                if self._nat is not None:
                    self._h = self._nat.cdll.hvd_mtr_create()
                self._ready = True

    def __del__(self):
        if getattr(self, "_h", None) and self._nat:
            try:
                self._nat.cdll.hvd_mtr_destroy(self._h)
            except Exception:
                pass

    def inc(self, amount: float = 1.0) -> None:
        if not self._ready:
            self._resolve()
        if self._h is not None:
            self._nat.cdll.hvd_mtr_add(self._h, float(amount))
            return
        with self._lock:
            self._v += amount

    def set(self, value: float) -> None:
        if not self._ready:
            self._resolve()
        if self._h is not None:
            self._nat.cdll.hvd_mtr_set(self._h, float(value))
            return
        with self._lock:
            self._v = float(value)

    def get(self) -> float:
        if not self._ready:
            self._resolve()
        if self._h is not None:
            return float(self._nat.cdll.hvd_mtr_get(self._h))
        with self._lock:
            return self._v


class Counter:
    """Monotonic counter child. ``inc(n)`` only; negative increments raise
    (Prometheus counter semantics)."""

    __slots__ = ("_cell", "_registry")

    def __init__(self, registry: "Registry"):
        self._registry = registry
        self._cell = _Cell()

    def inc(self, amount: float = 1.0) -> None:
        if not self._registry.enabled:
            return
        if amount < 0:
            raise ValueError("counters can only increase; use a gauge")
        self._cell.inc(amount)

    def get(self) -> float:
        return self._cell.get()


class Gauge:
    """Settable gauge child."""

    __slots__ = ("_cell", "_registry")

    def __init__(self, registry: "Registry"):
        self._registry = registry
        self._cell = _Cell()

    def set(self, value: float) -> None:
        if not self._registry.enabled:
            return
        self._cell.set(value)

    def inc(self, amount: float = 1.0) -> None:
        if not self._registry.enabled:
            return
        self._cell.inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def get(self) -> float:
        return self._cell.get()


class Histogram:
    """Fixed-bucket histogram child. Buckets are upper bounds (``le``);
    an implicit ``+Inf`` bucket closes the distribution. Native-backed
    (one atomic bucket add + CAS sum add) when built."""

    __slots__ = ("_nat", "_h", "_ready", "_lock", "_bounds", "_counts",
                 "_sum", "_count", "_registry", "_exemplar")

    def __init__(self, registry: "Registry", buckets: Sequence[float]):
        self._registry = registry
        self._bounds = tuple(sorted(float(b) for b in buckets))
        if not self._bounds:
            raise ValueError("histogram needs at least one bucket bound")
        # native backing resolves lazily on first use (see _Cell)
        self._ready = False
        self._nat = None
        self._h = None
        self._lock = _locks.lock("metrics.Histogram._lock")
        self._counts = [0] * (len(self._bounds) + 1)
        self._sum = 0.0
        self._count = 0
        self._exemplar = None

    def _resolve(self) -> None:
        with self._lock:
            if not self._ready:
                self._nat = _native_get()
                if self._nat is not None:
                    import ctypes
                    arr = (ctypes.c_double * len(self._bounds))(*self._bounds)
                    self._h = self._nat.cdll.hvd_hist_create(
                        arr, len(self._bounds))
                self._ready = True

    def __del__(self):
        if getattr(self, "_h", None) and self._nat:
            try:
                self._nat.cdll.hvd_hist_destroy(self._h)
            except Exception:
                pass

    def observe(self, value: float, exemplar: Optional[str] = None) -> None:
        if not self._registry.enabled:
            return
        if not self._ready:
            self._resolve()
        v = float(value)
        if exemplar:
            # trace-id exemplar (OpenMetrics-style): the most recent
            # traced observation, kept Python-side on BOTH backends so a
            # p99 outlier links to its request trace regardless of the
            # native fast path. Last-writer-wins under the GIL; the text
            # exposition stays 0.0.4 (exemplars are a scrape-format
            # feature, this is a debugging handle).
            self._exemplar = (str(exemplar), v)
        if self._h is not None:
            self._nat.cdll.hvd_hist_observe(self._h, v)
            return
        import bisect
        idx = bisect.bisect_left(self._bounds, v)
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1

    def exemplar(self) -> Optional[Tuple[str, float]]:
        """(trace id, observed value) of the most recent observation
        that carried one, or None."""
        return self._exemplar

    def read(self) -> Tuple[Tuple[int, ...], float, int]:
        """(per-bucket counts incl. +Inf, sum, count) — non-cumulative."""
        if not self._ready:
            self._resolve()
        if self._h is not None:
            import ctypes
            n = len(self._bounds) + 1
            counts = (ctypes.c_uint64 * n)()
            s = ctypes.c_double(0.0)
            total = ctypes.c_uint64(0)
            self._nat.cdll.hvd_hist_read(
                self._h, counts, ctypes.byref(s), ctypes.byref(total))
            return tuple(int(c) for c in counts), float(s.value), \
                int(total.value)
        with self._lock:
            return tuple(self._counts), self._sum, self._count

    @property
    def buckets(self) -> Tuple[float, ...]:
        return self._bounds

    def value(self) -> dict:
        """Snapshot form: cumulative Prometheus-style buckets."""
        counts, total_sum, total = self.read()
        acc = 0
        buckets = {}
        for b, c in zip(self._bounds, counts):
            acc += c
            buckets[_fmt(b)] = acc
        buckets["+Inf"] = total
        return {"buckets": buckets, "sum": total_sum, "count": total}


class Family:
    """A named metric family: one Prometheus name + help + type, with
    children per label-value combination (no labels = one anonymous
    child). ``labels()`` caches children, so steady-state lookups are one
    dict hit."""

    def __init__(self, registry: "Registry", name: str, help: str,
                 kind: str, labelnames: Tuple[str, ...] = (),
                 buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.help = help
        self.kind = kind            # "counter" | "gauge" | "histogram"
        self.labelnames = labelnames
        self._buckets = tuple(sorted(float(b) for b in buckets)) if buckets \
            else (DEFAULT_LATENCY_BUCKETS if kind == "histogram" else None)
        self._registry = registry
        self._lock = _locks.lock("metrics.Family._lock")
        self._children: Dict[Tuple[str, ...], object] = {}
        if not labelnames:
            self._children[()] = self._make_child()

    def _make_child(self):
        if self.kind == "counter":
            return Counter(self._registry)
        if self.kind == "gauge":
            return Gauge(self._registry)
        return Histogram(self._registry, self._buckets)

    def labels(self, **labelvalues: str):
        """Child for one label-value combination (created on first use)."""
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name} takes labels {self.labelnames}, "
                f"got {tuple(labelvalues)}")
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = self._children[key] = self._make_child()
        return child

    # unlabeled convenience: family behaves as its single child --------------
    def inc(self, amount: float = 1.0) -> None:
        self._children[()].inc(amount)

    def set(self, value: float) -> None:
        self._children[()].set(value)

    def dec(self, amount: float = 1.0) -> None:
        self._children[()].dec(amount)

    def observe(self, value: float, exemplar: Optional[str] = None) -> None:
        self._children[()].observe(value, exemplar=exemplar)

    def get(self):
        return self._children[()].get()

    def children(self) -> Iterable[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())

    def series_name(self, key: Tuple[str, ...]) -> str:
        if not key:
            return self.name
        inner = ",".join(
            f'{n}="{_escape_label(v)}"'
            for n, v in zip(self.labelnames, key))
        return f"{self.name}{{{inner}}}"


class Registry:
    """Thread-safe collection of metric families.

    ``enabled`` gates every write: a disabled registry (HVD_TPU_METRICS=0)
    costs one attribute check per instrumentation point. Registration is
    idempotent by name — re-registering returns the existing family, so
    module reloads and repeated ``init()`` cycles share one set of cells
    (the reference keeps its timeline/stall state process-global the same
    way)."""

    def __init__(self):
        self.enabled = True
        self._lock = _locks.lock("metrics.Registry._lock")
        self._families: Dict[str, Family] = {}

    def _register(self, name: str, help: str, kind: str,
                  labels: Tuple[str, ...], buckets=None) -> Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.labelnames}")
                if kind == "histogram":
                    want = tuple(sorted(float(b) for b in buckets)) \
                        if buckets else DEFAULT_LATENCY_BUCKETS
                    if want != fam._buckets:
                        # silently returning the old layout would file
                        # the caller's observations into wrong buckets
                        raise ValueError(
                            f"histogram {name!r} already registered with "
                            f"buckets {fam._buckets}, not {want}")
                return fam
            fam = Family(self, name, help, kind, tuple(labels),
                         buckets=buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Family:
        return self._register(name, help, "counter", tuple(labels))

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Family:
        return self._register(name, help, "gauge", tuple(labels))

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Family:
        return self._register(name, help, "histogram", tuple(labels),
                              buckets=buckets)

    def families(self) -> Iterable[Family]:
        with self._lock:
            return [self._families[n] for n in sorted(self._families)]

    def snapshot(self) -> Dict[str, object]:
        """Plain dict of every series: scalar floats for counters/gauges,
        ``{"buckets": {le: cumulative}, "sum": s, "count": n}`` for
        histograms. Keys are full series names (labels rendered
        Prometheus-style) in deterministic sorted order."""
        out: Dict[str, object] = {}
        for fam in self.families():
            for key, child in fam.children():
                name = fam.series_name(key)
                if fam.kind == "histogram":
                    out[name] = child.value()
                else:
                    out[name] = child.get()
        return dict(sorted(out.items()))

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines = []
        for fam in self.families():
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for key, child in fam.children():
                labelpairs = list(zip(fam.labelnames, key))
                if fam.kind == "histogram":
                    counts, total_sum, total = child.read()
                    acc = 0
                    for b, c in zip(child.buckets, counts):
                        acc += c
                        le = labelpairs + [("le", _fmt(b))]
                        inner = ",".join(
                            f'{n}="{_escape_label(str(v))}"'
                            for n, v in le)
                        lines.append(
                            f"{fam.name}_bucket{{{inner}}} {acc}")
                    inner = ",".join(
                        f'{n}="{_escape_label(str(v))}"'
                        for n, v in labelpairs + [("le", "+Inf")])
                    lines.append(f"{fam.name}_bucket{{{inner}}} {total}")
                    suffix = ""
                    if labelpairs:
                        suffix = "{" + ",".join(
                            f'{n}="{_escape_label(str(v))}"'
                            for n, v in labelpairs) + "}"
                    lines.append(f"{fam.name}_sum{suffix} {_fmt(total_sum)}")
                    lines.append(f"{fam.name}_count{suffix} {total}")
                else:
                    lines.append(
                        f"{fam.series_name(key)} {_fmt(child.get())}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Drop every family (tests only — production counters are
        monotonic for the life of the process)."""
        with self._lock:
            self._families.clear()


#: The process-global default registry every subsystem instruments.
REGISTRY = Registry()


def counter(name: str, help: str = "", labels: Sequence[str] = ()) -> Family:
    return REGISTRY.counter(name, help, labels)


def gauge(name: str, help: str = "", labels: Sequence[str] = ()) -> Family:
    return REGISTRY.gauge(name, help, labels)


def histogram(name: str, help: str = "", labels: Sequence[str] = (),
              buckets: Optional[Sequence[float]] = None) -> Family:
    return REGISTRY.histogram(name, help, labels, buckets=buckets)


def snapshot() -> Dict[str, object]:
    """Public read path #1: every series as a plain dict
    (``hvd.metrics_snapshot()``)."""
    return REGISTRY.snapshot()


def render_prometheus() -> str:
    return REGISTRY.render_prometheus()


# ---------------------------------------------------------------------------
# Read path #2: Prometheus HTTP exposition.
# ---------------------------------------------------------------------------

def start_http_server(port: int, addr: str = "0.0.0.0",
                      registry: Optional[Registry] = None):
    """Serve ``GET /metrics`` (Prometheus text format) on ``port``.
    Returns the server object; ``stop_http_server(server)`` tears it
    down. A daemon thread serves (shared stdlib plumbing in
    :mod:`horovod_tpu._http`), so a wedged scraper never blocks
    training."""
    from . import _http

    reg = registry or REGISTRY

    class _Handler(_http.QuietHandler):
        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
            path = self.path.split("?", 1)[0]
            if path not in ("/metrics", "/"):
                self.send_response(404)
                # HTTP/1.1 keep-alive (QuietHandler): a bodyless reply
                # still needs an explicit length or the client hangs
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            body = reg.render_prometheus().encode("utf-8")
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    return _http.start_server(_Handler, port=port, addr=addr,
                              name="hvd-tpu-metrics-http")


def stop_http_server(server) -> None:
    from . import _http
    _http.stop_server(server)


def configure(world):
    """Apply the metrics knobs at ``init()``: gate the registry on
    ``HVD_TPU_METRICS`` and start the exposition endpoint when
    ``HVD_TPU_METRICS_PORT`` is set (rank 0 only unless
    ``HVD_TPU_METRICS_ALL_RANKS``). Returns the HTTP server or None;
    ``basics.shutdown()`` stops it."""
    cfg = world.config
    REGISTRY.enabled = bool(cfg.get(_config.METRICS))
    port = int(cfg.get(_config.METRICS_PORT))
    if not REGISTRY.enabled or port <= 0:
        return None
    if world.process_id != 0 and not cfg.get(_config.METRICS_ALL_RANKS):
        return None
    try:
        return start_http_server(port, addr=cfg.get(_config.METRICS_ADDR))
    except (OSError, OverflowError, ValueError) as e:
        # an occupied port (two all-ranks processes on one host), a
        # port out of range (>65535 raises OverflowError, not OSError),
        # or a bad bind address must not kill training — metrics are
        # advisory
        import logging
        logging.getLogger("horovod_tpu").warning(
            "metrics: could not bind exposition endpoint on port %d: %s",
            port, e)
        return None


# ---------------------------------------------------------------------------
# Read path #3: cross-rank aggregation.
# ---------------------------------------------------------------------------

def _merge_hist(a: dict, b: dict) -> dict:
    buckets = dict(a["buckets"])
    for le, c in b["buckets"].items():
        buckets[le] = buckets.get(le, 0) + c
    return {"buckets": buckets, "sum": a["sum"] + b["sum"],
            "count": a["count"] + b["count"]}


def aggregate(per_rank: Sequence[Dict[str, object]]) -> Dict[str, object]:
    """Merge per-rank snapshots into one skew-revealing view: scalar
    series become ``{"sum", "min", "max"}`` (a large max-min spread IS
    the skew signal — one rank's cache-miss storm or latency tail),
    histograms merge bucket-wise."""
    out: Dict[str, object] = {}
    for snap in per_rank:
        for name, v in snap.items():
            if isinstance(v, dict):
                out[name] = _merge_hist(out[name], v) if name in out \
                    else dict(v)
            else:
                cur = out.get(name)
                if cur is None:
                    out[name] = {"sum": v, "min": v, "max": v}
                else:
                    cur["sum"] += v
                    cur["min"] = min(cur["min"], v)
                    cur["max"] = max(cur["max"], v)
    return dict(sorted(out.items()))


def metrics_allgather_summary() -> Dict[str, object]:
    """Allgather every rank's snapshot and return
    ``{"per_rank": [snap_rank0, ...], "aggregate": {...}}`` — the
    coordinator's one-call view of cross-rank skew. This is a collective:
    every process must call it together (like any eager collective).
    Requires ``hvd.init()``."""
    from . import functions as _functions
    snap = snapshot()
    per_rank = _functions.allgather_object(
        snap, name="hvd_tpu.metrics.summary")
    return {"per_rank": per_rank, "aggregate": aggregate(per_rank)}


def dump(path: str) -> None:
    """Write the current snapshot as JSON (operator convenience for
    postmortems without a scraper)."""
    with open(path, "w") as f:
        json.dump(snapshot(), f, indent=2, sort_keys=True)
