"""Per-request distributed tracing for horovod_tpu.

The chrome-tracing timeline (timeline.py) answers *what was this
process doing*; this module answers *where did this request's time go*
across every process it touched. A trace is keyed by the serving
request id (``X-HVD-TPU-Request-Id``) and is made of spans — one named
interval per layer the request crossed:

==========================  =================================================
span                        emitted by
==========================  =================================================
``router.route``            ``FleetRouter._proxy`` — root span on the router
``router.admission``        ``FairScheduler.acquire`` (fair-queue wait)
``server.infer`` /          replica HTTP handler; child of the router span
``server.generate``         via the ``X-HVD-TPU-Trace-Parent`` header
``batch.queue``             MicroBatcher admission -> dispatch coalescing wait
``batch.forward``           the padded micro-batch forward
``gen.prefill``             ContinuousBatcher, one span per prefill chunk
``gen.decode``              one span per decode step that emitted a token
``gen.preempt``             KV-block preemption (the recompute is the next
                            ``gen.prefill`` under the same trace)
``collective:<verb>:<name>``  eager collective submission, via the
                            ``collectives._record_round`` hook
==========================  =================================================

Each span records trace id, span id, parent span id, the owning rank,
an **epoch**-microsecond start timestamp (``time.time()`` — the one
clock comparable across hosts; durations are measured on the monotonic
clock) and free-form args. Spans collect per process in a bounded ring,
stream to a per-rank ``spans-rank<N>.jsonl`` file when
``HVD_TPU_TRACE_DIR`` is set (through timeline.py's bounded
``RecordWriter``, so a dead disk drops records into
``hvd_tpu_timeline_dropped_total`` instead of growing a queue), and
publish best-effort to the rendezvous ``trace`` KV scope for live
fleets. ``python -m tools.trace`` merges either source into one
cross-host chrome://tracing timeline for a request id.

Sampling is head-based and deterministic: ``HVD_TPU_TRACE_SAMPLE`` is
the traced fraction, and the decision is a hash of the request id (not
``hash()`` — PYTHONHASHSEED must not split the decision across hosts),
so the router and every replica rank independently agree on whether a
request is traced with zero coordination. The default 0 disables
tracing entirely; the hot-path cost is then one module-global load and
an is-None test per call site, the same discipline ``_schedule.record``
and the timeline's no-op guard follow.
"""

import collections
import hashlib
import json
import os
import threading
import time
import uuid
from typing import Optional

from . import _locks

__all__ = ["TraceContext", "Tracer", "Span", "tracer", "reset",
           "request_span", "span", "span_for", "emit_span", "collective",
           "current", "set_current", "sampled", "note_request",
           "last_request_id", "new_request_id", "TRACE_PARENT_HEADER",
           "ATTEMPT_HEADER", "KV_SCOPE"]

#: header carrying the upstream hop's encoded TraceContext so a
#: replica's server span nests under the router's proxy span
TRACE_PARENT_HEADER = "X-HVD-TPU-Trace-Parent"

#: attempt ordinal for a request's forwarded tries (0 = first send; a
#: hedge, connect-error failover, or mid-stream resume increments it).
#: The router keeps TRACE_PARENT_HEADER and the request id UNCHANGED
#: across re-submissions and stamps this instead, so every attempt's
#: spans land in the one trace, numbered, rather than minting
#: fresh-looking requests
ATTEMPT_HEADER = "X-HVD-TPU-Attempt"

#: rendezvous KV scope holding each rank's published span list
KV_SCOPE = "trace"

#: spans retained in the per-process ring (oldest evicted first); the
#: jsonl span file, when configured, keeps everything the writer's
#: bounded queue admitted
_BUFFER_DEPTH = 8192

_TRACER: Optional["Tracer"] = None
_RESOLVED = False
_RESOLVE_LOCK = threading.Lock()

_tls = threading.local()

#: last request id whose work touched this process — stamped into
#: StallError and preemption/deadline log lines regardless of the
#: sampling knob (failure attribution must not depend on tracing being
#: on). A bare global assignment: the one writer race (two concurrent
#: requests) just picks one of two truthful answers.
_LAST_REQUEST: Optional[str] = None


def note_request(request_id: Optional[str]) -> None:
    """Remember ``request_id`` as the most recent request this process
    worked for (see ``last_request_id``)."""
    global _LAST_REQUEST
    if request_id:
        _LAST_REQUEST = request_id


def last_request_id() -> Optional[str]:
    """The most recently noted request id, or None. Used by the stall
    inspector and the generation scheduler to say *whose* request was
    in flight when something went wrong."""
    return _LAST_REQUEST


def new_request_id() -> str:
    """A server-generated request id for clients that sent none —
    the same 16-hex shape the router mints."""
    return uuid.uuid4().hex[:16]


class TraceContext:
    """Identity of one request's trace as it crosses threads and
    hosts: the trace id plus the span the next child should nest
    under. ``encode``/``decode`` round-trip it through an HTTP header
    or a KV value."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def encode(self) -> str:
        return f"{self.trace_id}:{self.span_id}"

    @classmethod
    def decode(cls, raw) -> Optional["TraceContext"]:
        if not raw or not isinstance(raw, str) or ":" not in raw:
            return None
        trace_id, span_id = raw.split(":", 1)
        if not trace_id:
            return None
        return cls(trace_id, span_id)

    def __repr__(self):
        return f"TraceContext({self.trace_id!r}, {self.span_id!r})"


def current() -> Optional[TraceContext]:
    """The calling thread's active trace context, or None."""
    return getattr(_tls, "ctx", None)


def set_current(ctx: Optional[TraceContext]) -> Optional[TraceContext]:
    """Install ``ctx`` as the thread's context; returns the previous
    one so callers can restore it."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    return prev


def sampled(trace_id: str, rate: float) -> bool:
    """The deterministic head-sampling decision for ``trace_id``: true
    for a ``rate`` fraction of ids, computed identically on every
    process from a sha1 of the id."""
    if rate <= 0.0 or not trace_id:
        return False
    if rate >= 1.0:
        return True
    h = int(hashlib.sha1(trace_id.encode()).hexdigest()[:8], 16)
    return h / float(0x100000000) < rate


class Tracer:
    """Per-process span collector: bounded in-memory ring, optional
    per-rank jsonl span file, best-effort KV publish. One instance per
    process, resolved lazily by :func:`tracer`."""

    def __init__(self, rate: float, trace_dir: str = ""):
        self.rate = float(rate)
        self._dir = trace_dir or ""
        self._lock = _locks.lock("tracing.Tracer._lock")
        self._spans: "collections.deque" = collections.deque(
            maxlen=_BUFFER_DEPTH)
        self._writer = None
        self._writer_resolved = False
        self.span_path: Optional[str] = None
        self._client = None
        self._client_resolved = False
        self._rank: Optional[int] = None

    # -- identity ------------------------------------------------------------
    def rank(self) -> int:
        if self._rank is None:
            from . import basics
            if basics.is_initialized():
                self._rank = basics.world().rank()
            else:
                try:
                    self._rank = int(os.environ.get("HVD_TPU_RANK") or 0)
                except ValueError:
                    self._rank = 0
        return self._rank

    # -- collection ----------------------------------------------------------
    def emit(self, name: str, trace_id: str, span_id: str,
             parent_id: Optional[str], ts_us: float, dur_us: float,
             args: Optional[dict] = None) -> None:
        span = {"trace": trace_id, "span": span_id, "parent": parent_id,
                "name": name, "rank": self.rank(), "ts": ts_us,
                "dur": dur_us}
        if args:
            span["args"] = args
        with self._lock:
            self._spans.append(span)
        w = self._file_writer()
        if w is not None:
            w.put(span)

    def spans(self, trace_id: Optional[str] = None) -> list:
        """Snapshot of the in-memory ring, optionally filtered to one
        trace id (oldest first)."""
        with self._lock:
            out = list(self._spans)
        if trace_id is not None:
            out = [s for s in out if s["trace"] == trace_id]
        return out

    # -- span file (shared bounded writer with timeline.py) ------------------
    def _file_writer(self):
        if self._writer_resolved:
            return self._writer
        with self._lock:
            if not self._writer_resolved:
                if self._dir:
                    from .timeline import RecordWriter
                    os.makedirs(self._dir, exist_ok=True)
                    self.span_path = os.path.join(
                        self._dir, f"spans-rank{self.rank()}.jsonl")
                    self._writer = RecordWriter(self.span_path,
                                                mode="jsonl")
                self._writer_resolved = True
        return self._writer

    # -- KV publication (live fleets) ----------------------------------------
    def _kv_client(self):
        """A rendezvous KV client when the launcher's server is
        reachable from config, else None — same single-attempt,
        short-timeout recipe as ``_schedule.ScheduleLedger``: publishes
        ride the request path, so a dead KV server must cost one
        bounded probe, never a retry chain."""
        if not self._client_resolved:
            from . import config as _config
            from . import retry as _retry
            cfg = _config.live_config()
            addr = cfg.get(_config.RENDEZVOUS_ADDR)
            port = cfg.get(_config.RENDEZVOUS_PORT)
            if addr and port and int(port) > 0:
                from .runner.rendezvous import KVStoreClient
                self._client = KVStoreClient(
                    addr, int(port), timeout=2.0,
                    retry=_retry.RetryPolicy(
                        max_attempts=1, initial_backoff=0.05,
                        max_backoff=0.1, deadline=2.0))
            self._client_resolved = True
        return self._client

    def publish(self) -> bool:
        """Best-effort publish of the in-memory ring to the rendezvous
        ``trace`` scope (key ``rank<N>``) so ``tools/trace --kv`` can
        merge a live fleet's spans without touching its disks. Returns
        True when the PUT landed."""
        client = self._kv_client()
        if client is None:
            return False
        payload = json.dumps(self.spans())
        try:
            client.put(KV_SCOPE, f"rank{self.rank()}", payload.encode())
            return True
        except Exception:
            return False

    def close(self) -> None:
        w = self._writer
        if w is not None:
            w.close()


def tracer() -> Optional[Tracer]:
    """The process tracer when ``HVD_TPU_TRACE_SAMPLE`` > 0, else None.
    Resolved once; :func:`reset` re-reads the knobs."""
    global _TRACER, _RESOLVED
    if not _RESOLVED:
        with _RESOLVE_LOCK:
            if not _RESOLVED:
                from . import config as _config
                cfg = _config.live_config()
                rate = float(cfg.get(_config.TRACE_SAMPLE))
                _TRACER = Tracer(rate, cfg.get(_config.TRACE_DIR)) \
                    if rate > 0.0 else None
                _RESOLVED = True
    return _TRACER


def reset() -> None:
    """Close the span writer, drop the tracer and the thread's context,
    and re-read the knobs — tests and elastic resets."""
    global _TRACER, _RESOLVED, _LAST_REQUEST
    tr = _TRACER
    if tr is not None:
        try:
            tr.close()
        except Exception:
            pass
    with _RESOLVE_LOCK:
        _TRACER = None
        _RESOLVED = False
    _LAST_REQUEST = None
    _tls.ctx = None


# ---------------------------------------------------------------------------
# span context managers
# ---------------------------------------------------------------------------

class _NullSpan:
    """Singleton no-op span: what every span helper returns when the
    tracer is off or the request is unsampled."""

    __slots__ = ()
    span_id = None
    trace_id = None
    sampled = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate(self, **kw):
        pass

    def context(self):
        return None


_NULL_SPAN = _NullSpan()


class Span:
    """Context manager recording one span. Entering installs the span
    as the thread's current context (so nested ``span()`` calls and
    collective submissions bind under it); exiting restores the
    previous context and emits the record."""

    __slots__ = ("_tr", "name", "trace_id", "span_id", "parent_id",
                 "_args", "_ts", "_t0", "_prev")

    sampled = True

    def __init__(self, tr: Tracer, name: str, trace_id: str,
                 parent_id: Optional[str], args: Optional[dict] = None):
        self._tr = tr
        self.name = name
        self.trace_id = trace_id
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        self._args = dict(args) if args else None

    def annotate(self, **kw) -> None:
        """Attach args to the span before it closes."""
        if self._args is None:
            self._args = {}
        self._args.update(kw)

    def context(self) -> TraceContext:
        """A TraceContext naming this span as the parent — for header
        propagation (``TRACE_PARENT_HEADER``) or KV handoff."""
        return TraceContext(self.trace_id, self.span_id)

    def __enter__(self):
        self._prev = set_current(TraceContext(self.trace_id, self.span_id))
        self._ts = time.time() * 1e6
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, etype, exc, tb):
        dur = (time.perf_counter() - self._t0) * 1e6
        set_current(self._prev)
        if etype is not None:
            self.annotate(error=repr(exc))
        self._tr.emit(self.name, self.trace_id, self.span_id,
                      self.parent_id, self._ts, dur, self._args)
        return False


def request_span(name: str, request_id: Optional[str],
                 parent: Optional[str] = None,
                 args: Optional[dict] = None):
    """Root span for a request arriving at this process. Returns a
    no-op unless the tracer is on AND the deterministic head-sampling
    decision for ``request_id`` says trace. ``parent`` is the upstream
    hop's encoded context (the ``X-HVD-TPU-Trace-Parent`` header), so a
    replica's server span nests under the router's proxy span. Always
    notes the request id for failure attribution, sampled or not."""
    note_request(request_id)
    tr = _TRACER if _RESOLVED else tracer()
    if tr is None or not request_id or not sampled(request_id, tr.rate):
        return _NULL_SPAN
    parent_id = None
    if parent:
        ctx = TraceContext.decode(parent)
        if ctx is not None and ctx.trace_id == request_id:
            parent_id = ctx.span_id
    return Span(tr, name, request_id, parent_id, args)


def span(name: str, args: Optional[dict] = None):
    """Child span under the calling thread's current context; a no-op
    when the thread carries no sampled request."""
    tr = _TRACER if _RESOLVED else tracer()
    if tr is None:
        return _NULL_SPAN
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        return _NULL_SPAN
    return Span(tr, name, ctx.trace_id, ctx.span_id, args)


def span_for(ctx: Optional[TraceContext], name: str,
             args: Optional[dict] = None):
    """Child span bound to an explicit context — for worker threads
    (batcher dispatch, generation scheduler) that carry the request's
    context in a data structure rather than thread-local state."""
    tr = _TRACER if _RESOLVED else tracer()
    if tr is None or ctx is None:
        return _NULL_SPAN
    return Span(tr, name, ctx.trace_id, ctx.span_id, args)


def emit_span(ctx: Optional[TraceContext], name: str,
              start_monotonic: float,
              end_monotonic: Optional[float] = None,
              args: Optional[dict] = None) -> None:
    """Record a span for an interval measured on ``time.monotonic()``
    that already ended when tracing code ran — the batcher's queue wait
    is only known at dispatch. The interval is mapped onto the epoch
    clock through the current monotonic/epoch pair."""
    tr = _TRACER if _RESOLVED else tracer()
    if tr is None or ctx is None:
        return
    now_mono = time.monotonic()
    end_mono = now_mono if end_monotonic is None else end_monotonic
    ts = time.time() * 1e6 - (now_mono - start_monotonic) * 1e6
    dur = max(0.0, (end_mono - start_monotonic) * 1e6)
    tr.emit(name, ctx.trace_id, uuid.uuid4().hex[:16], ctx.span_id,
            ts, dur, args)


def collective(entry: tuple) -> None:
    """``collectives._record_round`` hook: an instant span naming the
    submitted collective's verb and tensor name, bound to whatever
    sampled request the submitting thread is working for. The first
    line is the zero-overhead guard — with ``HVD_TPU_TRACE_SAMPLE=0``
    (the default) this costs one module-global load and an is-None
    test per collective submission."""
    tr = _TRACER if _RESOLVED else tracer()
    if tr is None:
        return
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        return
    tr.emit(f"collective:{entry[0]}:{entry[1]}", ctx.trace_id,
            uuid.uuid4().hex[:16], ctx.span_id, time.time() * 1e6, 0.0)
